"""The one logging facade every training loop talks to.

Replaces the seven hand-rolled ``init_wandb`` / ``wandb_run.log(...)`` blocks
that were copy-pasted across ``training/train_*.py``: a loop builds ONE
:class:`RunTelemetry` (or receives one via its ``telemetry=`` kwarg) and
routes metrics through :meth:`RunTelemetry.log_step`. wandb remains optional
exactly as before — when ``wb=True`` and wandb imports, metrics reach it with
the SAME keys the loops always used; otherwise they only reach the registry
and the JSONL sink.

Module-level helpers (``get_registry`` / ``warn_once``) expose a process
default registry for call sites with no run in scope (e.g.
``utils/profiling.py``'s unknown-device-kind warning).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from agilerl_tpu.observability.events import JsonlSink, NullSink
from agilerl_tpu.observability.lineage import LineageTracker
from agilerl_tpu.observability.registry import MetricsRegistry
from agilerl_tpu.observability.timeline import StepTimeline

#: env var: write run telemetry JSONL here when no explicit path is given
#: (a directory gets one file per run; a ``.jsonl`` path is used verbatim)
TELEMETRY_ENV = "AGILERL_TPU_TELEMETRY"
#: env var: emit a JSONL ``step`` event every N steps (default 1). Hot
#: per-env-step loops with a JsonlSink should raise this — each step event
#: is a flushed disk write. 0 disables step events; aggregates stay exact.
STEP_EVERY_ENV = "AGILERL_TPU_TELEMETRY_STEP_EVERY"
#: env var: distributed-tracing sample rate (a float in [0, 1]; 0 =
#: anomaly-only — forced spans still record). Requires a live JSONL sink
#: (``AGILERL_TPU_TELEMETRY`` or an explicit ``jsonl_path``): spans ride
#: the same event stream. Unset = tracing stays a no-op.
TRACE_ENV = "AGILERL_TPU_TRACE"

_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """Process-default registry (used by call sites with no run in scope)."""
    return _default_registry


def warn_once(key: str, message: str, **fields: Any) -> bool:
    return _default_registry.warn_once(key, message, **fields)


def _resolve_jsonl_path(jsonl_path: Optional[str]) -> Optional[str]:
    path = jsonl_path or os.environ.get(TELEMETRY_ENV)
    if not path:
        return None
    if path.endswith(".jsonl"):
        return path
    os.makedirs(path, exist_ok=True)
    import time

    return os.path.join(path, f"run-{os.getpid()}-{int(time.time())}.jsonl")


class RunTelemetry:
    """Registry + sink + lineage + step timeline + optional wandb, for one
    training run."""

    def __init__(
        self,
        wb: bool = False,
        config: Optional[Dict] = None,
        jsonl_path: Optional[str] = None,
        registry: Optional[MetricsRegistry] = None,
        lineage: bool = True,
        name: str = "train",
        model_config=None,
        step_event_every: Optional[int] = None,
        project: str = "agilerl-tpu",
        trace: Optional[float] = None,
    ):
        if step_event_every is None:
            step_event_every = int(os.environ.get(STEP_EVERY_ENV, "1") or 1)
        self.registry = registry or MetricsRegistry()
        self._closed = False
        path = _resolve_jsonl_path(jsonl_path)
        sink = self.registry.sink
        # attach a live sink when: the registry has none, a previous run's
        # sink was closed, or a JSONL path is requested but only a NullSink
        # is attached (a live JsonlSink from the caller is respected)
        if (sink is None or getattr(sink, "closed", False)
                or (path and isinstance(sink, NullSink))):
            self.registry.attach_sink(JsonlSink(path) if path else NullSink())
            if path:
                # a crashed/interrupted run still gets its lineage_summary at
                # process exit; close() is idempotent so a normal close wins
                import atexit
                import weakref

                ref = weakref.ref(self)
                atexit.register(lambda: ref() and ref().close())
        self.lineage = LineageTracker(self.registry) if lineage else None
        if self.lineage is not None:
            # marks the tracker as facade-owned: attach_evolution may replace
            # it on HPO objects reused across runs (a user-wired tracker is
            # never clobbered)
            self.lineage._facade_owned = True
        self.timeline = StepTimeline(
            self.registry, name=name, model_config=model_config,
            step_event_every=step_event_every)
        # -- distributed tracing: spans ride the run's event sink. The
        # configured tracer is ALSO installed as the process default so
        # tracer-less components (fleet replicas, flywheel pods, elastic
        # controllers) pick it up through trace.get_tracer(); close()
        # restores the previous default.
        if trace is None:
            env_rate = os.environ.get(TRACE_ENV)
            if env_rate:
                trace = float(env_rate)
        self.tracer = None
        self._prev_tracer = None
        # trace=0.0 is a VALID configuration (anomaly-only: forced spans
        # still record) — only None/False leave tracing off
        if trace is not None and trace is not False:
            from agilerl_tpu.observability.trace import Tracer, set_tracer

            rate = 1.0 if trace is True else float(trace)
            sink = self.registry.sink
            if sink is not None and not isinstance(sink, NullSink):
                self.tracer = Tracer(sink=sink, sample_rate=rate,
                                     pod=f"{name}-{os.getpid()}",
                                     metrics=self.registry)
                self._prev_tracer = set_tracer(self.tracer)
        self._wandb = None
        if wb:
            from agilerl_tpu.utils.utils import init_wandb

            self._wandb = init_wandb(project=project, config=config)
        if config:
            self.registry.emit("run_config", config=config)

    # -- the deduplicated per-loop logging surface -------------------------
    def log_step(self, metrics: Dict[str, Any], kind: str = "metrics") -> None:
        """Route one metrics dict to wandb (when enabled) + the event sink —
        the single replacement for every ``if wandb_run is not None:
        wandb_run.log({...})`` block."""
        if self._wandb is not None:
            self._wandb.log(metrics)
        self.registry.emit(kind, **metrics)

    def step(self, **kwargs) -> Optional[Dict[str, Any]]:
        """Per-training-step timeline tick (see StepTimeline.step)."""
        return self.timeline.step(**kwargs)

    def record_eval(self, pop: List, fitnesses: List[float]) -> None:
        """Feed an evaluation's fitnesses to the lineage tracker (closing out
        the previous generation's parent→child records) and emit an ``eval``
        event."""
        if self.lineage is not None:
            for agent, f in zip(pop, fitnesses):
                self.lineage.record_fitness(agent.index, float(f))
        if fitnesses:
            mean = float(sum(float(f) for f in fitnesses) / len(fitnesses))
            self.registry.gauge("eval/mean_fitness").set(mean)
            self.registry.emit(
                "eval",
                mean_fitness=mean,
                fitnesses=[float(f) for f in fitnesses],
                agents=[int(a.index) for a in pop],
            )

    def attach_evolution(self, tournament, mutation) -> None:
        """Point the HPO machinery's lineage hooks at this run's tracker."""
        if self.lineage is None:
            return

        def _attachable(obj):
            existing = getattr(obj, "lineage", None)
            # replace nothing the caller wired in explicitly; a facade-owned
            # tracker from a PREVIOUS run must be replaced or generation
            # events would land in that run's closed sink
            return existing is None or getattr(existing, "_facade_owned", False)

        if tournament is not None and _attachable(tournament):
            tournament.lineage = self.lineage
        if mutation is not None and _attachable(mutation):
            mutation.lineage = self.lineage

    def close(self, lineage_path: Optional[str] = None) -> None:
        if self._closed:
            return
        self._closed = True
        if self.tracer is not None:
            from agilerl_tpu.observability import trace as _trace

            # only restore if this run's tracer is still the default (a
            # later run may have installed its own — don't clobber it)
            if _trace.get_tracer() is self.tracer:
                _trace.set_tracer(self._prev_tracer)
            self.tracer = None
        if self.lineage is not None:
            if lineage_path:
                self.lineage.dump(lineage_path)
            self.registry.emit("lineage_summary",
                               mutation_effects=self.lineage.mutation_effects())
        sink = self.registry.sink
        if sink is not None:
            sink.close()


def init_run_telemetry(
    wb: bool = False,
    config: Optional[Dict] = None,
    telemetry: Optional[RunTelemetry] = None,
    **kwargs,
) -> RunTelemetry:
    """The loops' one-liner: reuse a caller-supplied RunTelemetry or build a
    fresh one (wandb when ``wb``, JSONL when configured via arg/env)."""
    if telemetry is not None:
        return telemetry
    return RunTelemetry(wb=wb, config=config, **kwargs)
