"""Per-step timeline: step time, throughput, MFU, device memory.

Wraps :class:`agilerl_tpu.utils.profiling.StepTimer` and reuses the SAME
FLOPs accounting (``transformer_flops_per_token`` + ``PEAK_BF16_FLOPS``) so
the timeline's MFU and ``bench.py``'s MFU cannot drift. Multihost aggregation
rides :class:`agilerl_tpu.utils.log_utils.CombineLogs` — host-side weighted
means reduced over ``process_allgather``, no new collective machinery.

MFU caveats (see docs/observability.md): emitted only when the backend has a
defined bf16 peak (TPU); an unknown TPU generation falls back to the v5 peak
and every MFU reading is then tagged ``estimated=true``.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from agilerl_tpu.utils.log_utils import CombineLogs
from agilerl_tpu.utils.profiling import (
    StepTimer,
    peak_flops_info,
    transformer_flops_per_token,
)


def device_memory_stats(device=None) -> Dict[str, float]:
    """``{bytes_in_use, peak_bytes_in_use, bytes_limit}`` for the (first
    local) device; {} where the backend exposes no allocator stats (CPU)."""
    try:
        import jax

        device = device or jax.local_devices()[0]
        stats = device.memory_stats()
    except Exception:
        return {}
    if not stats:
        return {}
    out = {}
    for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
        if k in stats:
            out[k] = int(stats[k])
    return out


class StepTimeline:
    """Emit one ``step`` event per training step through a registry.

    ``step()`` is called once per host-visible training step; the timeline
    computes ``step_time_s`` (rolling window via StepTimer), optional
    ``env_steps_per_sec`` / ``tokens_per_sec``, and — when a model config and
    token count are given on a device with a defined peak — ``mfu``.
    """

    def __init__(
        self,
        registry,
        name: str = "train",
        model_config=None,
        window: int = 20,
        memory_stats_every: int = 50,
        step_event_every: int = 1,
    ):
        self.registry = registry
        self.name = name
        self.model_config = model_config
        self.timer = StepTimer(window=window)
        self.memory_stats_every = int(memory_stats_every)
        # histograms/gauges update every step; the JSONL `step` event is
        # emitted every Nth step (hot off-policy loops with a JsonlSink
        # should raise this — per-line flush on every env step is disk-bound;
        # 0 disables step events entirely)
        self.step_event_every = int(step_event_every)
        self.step_index = 0
        # O(1) running (sum, count) per metric: a 10M-step run must not grow
        # host memory; aggregate() feeds these into CombineLogs for the
        # cross-host reduce
        self._acc: Dict[str, Any] = {}
        # pass our registry so an unknown-chip fallback warning lands in THIS
        # run's event stream, not just the process-default registry
        peak, estimated = peak_flops_info(registry=registry)
        self._peak_flops = peak
        self._peak_estimated = estimated
        self._flops_per_token = (
            transformer_flops_per_token(model_config)
            if model_config is not None else None
        )

    def set_model_config(self, model_config) -> None:
        """(Re)bind the transformer config used for MFU accounting — loops
        that only learn the config from their population call this once."""
        self.model_config = model_config
        self._flops_per_token = (
            transformer_flops_per_token(model_config)
            if model_config is not None else None
        )

    def step(
        self,
        env_steps: int = 0,
        tokens: int = 0,
        agent_index: Optional[int] = None,
        metrics: Optional[Dict[str, float]] = None,
        host_time_s: Optional[float] = None,
        device_time_s: Optional[float] = None,
    ) -> Optional[Dict[str, Any]]:
        """Record one step. The FIRST call only arms the timer (no interval
        exists yet) and returns None. Histograms/gauges/aggregates update on
        every call; the JSONL ``step`` event (and its payload build + memory
        probe) happens every ``step_event_every``-th step — the method
        returns the payload when one was emitted, else None.

        ``host_time_s`` / ``device_time_s`` come from the pipelined interop
        loops (docs/performance.md): host = time actively stepping the env /
        staging on host; device = time the host spent BLOCKED on device
        results (action syncs + explicit cadence syncs). The derived
        ``overlap_fraction`` gauge is ``1 - device_time_s / step_time_s`` —
        the fraction of the step during which device work ran hidden under
        host work; it rises toward 1 as pipelining takes hold."""
        dt = self.timer.tick()
        if dt is None:
            return None
        env_rate = round(env_steps / dt, 2) if env_steps else None
        mfu = None
        if tokens and self._flops_per_token is not None and self._peak_flops:
            mfu = round(
                self._flops_per_token * tokens / (dt * self._peak_flops), 4)
        overlap = None
        if device_time_s is not None and dt > 0:
            overlap = round(min(max(1.0 - device_time_s / dt, 0.0), 1.0), 4)

        self.registry.histogram(
            f"{self.name}/step_time_s",
            help="per-step wall time").observe(dt)
        if env_rate is not None:
            self.registry.gauge(f"{self.name}/env_steps_per_sec").set(env_rate)
        if mfu is not None:
            self.registry.gauge(f"{self.name}/mfu").set(mfu)
        if host_time_s is not None:
            self.registry.gauge(f"{self.name}/host_time_s").set(host_time_s)
        if device_time_s is not None:
            self.registry.gauge(f"{self.name}/device_time_s").set(device_time_s)
        if overlap is not None:
            self.registry.gauge(f"{self.name}/overlap_fraction").set(overlap)
        self.registry.counter(f"{self.name}/steps_total").inc()
        for k, v in (("step_time_s", dt), ("env_steps_per_sec", env_rate),
                     ("mfu", mfu), ("host_time_s", host_time_s),
                     ("device_time_s", device_time_s),
                     ("overlap_fraction", overlap)):
            if v is not None:
                total, n = self._acc.get(k, (0.0, 0))
                self._acc[k] = (total + v, n + 1)

        emit = (self.step_event_every
                and self.step_index % self.step_event_every == 0)
        event: Optional[Dict[str, Any]] = None
        if emit:
            event = {
                "name": self.name,
                "step": self.step_index,
                "step_time_s": round(dt, 9),
            }
            if agent_index is not None:
                event["agent"] = int(agent_index)
            if env_rate is not None:
                event["env_steps_per_sec"] = env_rate
            if host_time_s is not None:
                event["host_time_s"] = round(host_time_s, 9)
            if device_time_s is not None:
                event["device_time_s"] = round(device_time_s, 9)
            if overlap is not None:
                event["overlap_fraction"] = overlap
            if tokens:
                event["tokens_per_sec"] = round(tokens / dt, 2)
                if mfu is not None:
                    event["mfu"] = mfu
                    event["estimated"] = bool(self._peak_estimated)
            if metrics:
                event.update({k: float(v) for k, v in metrics.items()})
            if (self.memory_stats_every
                    and self.step_index % self.memory_stats_every == 0):
                mem = device_memory_stats()
                if mem:
                    event["memory"] = mem
            self.registry.emit("step", **event)
        self.step_index += 1
        return event

    def aggregate(self, across_hosts: bool = False) -> Dict[str, float]:
        """Weighted-mean step metrics since the last aggregate() — reduced
        over every host when ``across_hosts`` (CombineLogs ride-along: each
        metric enters as its local mean weighted by its sample count)."""
        combine = CombineLogs()
        for k, (total, n) in self._acc.items():
            combine.accum({k: total / n}, weight=n)
        self._acc = {}
        return combine.reduce(across_hosts=across_hosts)


class PhaseTimer:
    """``with PhaseTimer(reg, "serving/prefill"): ...`` → histogram observe."""

    def __init__(self, registry, name: str, buckets=None):
        self.registry = registry
        self.name = name
        self.buckets = buckets
        self._t0 = None
        self.elapsed_s: Optional[float] = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed_s = time.perf_counter() - self._t0
        kwargs = {"buckets": self.buckets} if self.buckets is not None else {}
        self.registry.histogram(self.name, **kwargs).observe(self.elapsed_s)
        return False
