"""Metrics registry — counters, gauges, fixed-bucket histograms.

Dependency-free (stdlib + numpy-free on the hot path): production TPU stacks
treat per-step telemetry as a first-class subsystem (MegaScale, Jiang et al.
2024) rather than a pile of ad-hoc wandb dicts; this registry is the one
process-local store every layer (training loops, HPO, serving) writes into.

Values export two ways: a structured JSONL event stream (``events.JsonlSink``)
for timeline consumers (``bench.py``, offline analysis) and Prometheus-style
text exposition (:meth:`MetricsRegistry.prometheus_text`) for scrapers.
"""

from __future__ import annotations

import contextlib
import math
import threading
import time as _time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

#: default latency-ish buckets (seconds): ~exponential 1ms .. 60s
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def _sanitize(name: str) -> str:
    """Prometheus metric names: [a-zA-Z_:][a-zA-Z0-9_:]*."""
    out = "".join(c if c.isalnum() or c in "_:" else "_" for c in name)
    return out if out and not out[0].isdigit() else "_" + out


class Counter:
    """Monotonically increasing count."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (inc {n})")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Last-write-wins scalar."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = float("nan")

    def set(self, v: float) -> None:
        self._value = float(v)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Fixed-bucket histogram with interpolated percentiles.

    Buckets are upper bounds (a +inf overflow bucket is implicit). Percentiles
    interpolate linearly inside the containing bucket, Prometheus
    ``histogram_quantile`` style: the first finite bucket interpolates from 0
    (values are assumed non-negative — latencies, durations, depths), and any
    rank landing in the overflow bucket reports the largest finite bound (the
    histogram cannot see beyond it).
    """

    def __init__(self, name: str, buckets: Sequence[float] = DEFAULT_BUCKETS,
                 help: str = ""):
        if not buckets:
            raise ValueError("histogram needs at least one bucket bound")
        bounds = sorted(float(b) for b in buckets)
        if bounds != list(dict.fromkeys(bounds)):
            raise ValueError(f"duplicate bucket bounds in {bounds}")
        self.name = name
        self.help = help
        self.bounds: Tuple[float, ...] = tuple(bounds)
        self._counts = [0] * (len(bounds) + 1)  # last = +inf overflow
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        i = len(self.bounds)
        for j, b in enumerate(self.bounds):
            if v <= b:
                i = j
                break
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def percentile(self, q: float) -> float:
        """q in [0, 100]. NaN on an empty histogram."""
        if not 0 <= q <= 100:
            raise ValueError(f"percentile {q} outside [0, 100]")
        if self._count == 0:
            return float("nan")
        rank = (q / 100.0) * self._count
        cum = 0
        for i, c in enumerate(self._counts):
            prev_cum = cum
            cum += c
            if cum >= rank and c > 0:
                if i == len(self.bounds):
                    # overflow bucket: unbounded above, report the edge
                    return self.bounds[-1]
                lo = 0.0 if i == 0 else self.bounds[i - 1]
                hi = self.bounds[i]
                return lo + (hi - lo) * (rank - prev_cum) / c
        return self.bounds[-1]

    def summary(self) -> Dict[str, float]:
        return {
            "count": self._count,
            "sum": self._sum,
            "p50": self.percentile(50),
            "p95": self.percentile(95),
            "p99": self.percentile(99),
        }


class MetricsRegistry:
    """Process-local named metric store + warn-once + event fan-out.

    ``counter/gauge/histogram`` are get-or-create; re-requesting a name
    returns the same instrument (so call sites never coordinate). An attached
    sink (``events.JsonlSink``) receives every :meth:`emit` — the registry is
    the single funnel through which structured events reach disk.
    """

    def __init__(self, sink=None,
                 bucket_overrides: Optional[Dict[str, Sequence[float]]] = None):
        self._metrics: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self._sink = sink
        self._warned: set = set()
        #: sanitized Prometheus name -> original name (collision guard)
        self._sanitized: Dict[str, str] = {}
        #: histogram name -> configured bucket bounds (takes precedence over
        #: the call site's ``buckets=`` so an SLO spec can align bucket
        #: edges with its thresholds — interpolated percentiles are exact at
        #: an edge and an estimate inside a bucket)
        self._bucket_overrides: Dict[str, Tuple[float, ...]] = {}
        for name, bounds in (bucket_overrides or {}).items():
            self.configure_buckets(name, bounds)

    # -- instruments -------------------------------------------------------
    def _get_or_create(self, name: str, cls, **kwargs):
        collision: Optional[str] = None
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, **kwargs)
                self._metrics[name] = m
                pname = _sanitize(name)
                other = self._sanitized.setdefault(pname, name)
                if other != name:
                    collision = other
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
        if collision is not None:
            # outside the lock: warn_once re-enters it. Two DISTINCT metric
            # names sanitizing to one Prometheus name would silently merge
            # in prometheus_text() — scrapers would see two series under
            # one name and aggregate garbage
            self.warn_once(
                f"sanitize-collision:{name}",
                f"metric names {collision!r} and {name!r} both sanitize to "
                f"Prometheus name {_sanitize(name)!r}; their exposition "
                "lines will collide — rename one of them",
                first=collision, second=name)
        return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, Counter, help=help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(name, Gauge, help=help)

    def configure_buckets(self, name: str,
                          buckets: Sequence[float]) -> None:
        """Pin the bucket bounds future :meth:`histogram` calls for ``name``
        will use, overriding the call site's ``buckets=`` argument. This is
        how an SLO spec aligns bucket edges with its thresholds BEFORE the
        instrumented code path first observes (``observability.slo.
        SLOSpec.apply_buckets``). Configuring after the instrument exists
        with different bounds cannot rebin live data — it warns once and
        keeps the live instrument (every pod must be configured identically
        BEFORE traffic, or the cross-process aggregator's exact-merge check
        will raise ``TelemetrySchemaError``)."""
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("configure_buckets needs at least one bound")
        with self._lock:
            live = self._metrics.get(name)
        if isinstance(live, Histogram) and live.bounds != bounds:
            self.warn_once(
                f"bucket-config-late:{name}",
                f"configure_buckets({name!r}) after the histogram exists "
                f"with different bounds — live data cannot be rebinned; "
                "keeping the live bounds (configure before first observe)",
                configured=list(bounds), live=list(live.bounds))
            return
        self._bucket_overrides[name] = bounds

    def bucket_bounds(self, name: str) -> Optional[Tuple[float, ...]]:
        """The effective bucket bounds for ``name``: the live instrument's
        if created, else the configured override, else None."""
        with self._lock:
            live = self._metrics.get(name)
        if isinstance(live, Histogram):
            return live.bounds
        return self._bucket_overrides.get(name)

    def histogram(self, name: str, buckets: Sequence[float] = DEFAULT_BUCKETS,
                  help: str = "") -> Histogram:
        override = self._bucket_overrides.get(name)
        if override is not None:
            buckets = override
        h = self._get_or_create(name, Histogram, buckets=buckets, help=help)
        # fast path: call sites pass the same module-constant tuple every
        # time, so an elementwise equality short-circuits before the
        # sort+float normalization (this runs per observe on hot paths)
        if (override is None and tuple(buckets) != h.bounds
                and h.bounds != tuple(sorted(float(b) for b in buckets))):
            # two call sites disagree on bounds: the first one won (get-or-
            # create semantics), and silent skew would make interpolated
            # percentiles — and the aggregator's exact bucket-wise merge —
            # quietly wrong for whichever site loses
            self.warn_once(
                f"bucket-skew:{name}",
                f"histogram {name!r} requested with bucket bounds that "
                f"differ from the live instrument's — the first creation "
                "won; align the call sites (or configure_buckets up front)",
                live=list(h.bounds))
        return h

    def timer(self, name: str, help: str = ""):
        """Context manager accumulating the block's wall time into the
        counter ``name`` (seconds) — the idiom behind the time-attribution
        counters (``flywheel/learner_idle_s``, ``flywheel/decode_stall_s``,
        ``pipeline/sync_wait_s``-style accounting): a counter, not a
        histogram, because the question these answer is "how much of the
        run was spent HERE", which is a sum."""
        counter = self.counter(name, help=help)

        @contextlib.contextmanager
        def _timed():
            t0 = _time.perf_counter()
            try:
                yield counter
            finally:
                counter.inc(_time.perf_counter() - t0)

        return _timed()

    # -- events ------------------------------------------------------------
    def attach_sink(self, sink) -> None:
        self._sink = sink

    @property
    def sink(self):
        return self._sink

    def emit(self, kind: str, **fields: Any) -> None:
        """Write a structured event to the attached sink (no-op without one)."""
        if self._sink is not None:
            self._sink.emit(kind, fields)

    def warn_once(self, key: str, message: str, **fields: Any) -> bool:
        """Emit a ``warning`` event and bump ``warnings_total`` the FIRST time
        `key` is seen; later calls are no-ops. Returns True when emitted."""
        with self._lock:
            if key in self._warned:
                return False
            self._warned.add(key)
        self.counter("warnings_total", help="one-time warnings emitted").inc()
        self.emit("warning", key=key, message=message, **fields)
        import warnings

        warnings.warn(message, RuntimeWarning, stacklevel=3)
        return True

    # -- exposition --------------------------------------------------------
    def _items(self):
        # copy under the lock: a scraper thread must not race a first-use
        # metric insert ("dictionary changed size during iteration")
        with self._lock:
            return sorted(self._metrics.items())

    def snapshot(self) -> Dict[str, Any]:
        """Plain-dict view: counters/gauges → value, histograms → summary."""
        out: Dict[str, Any] = {}
        for name, m in self._items():
            out[name] = m.summary() if isinstance(m, Histogram) else m.value
        return out

    def dump(self) -> Dict[str, Any]:
        """FULL-resolution state for the cross-process telemetry plane
        (``observability/export.py``): counters/gauges as raw values,
        histograms as ``{bounds, counts, sum, count}`` — the mergeable
        form (percentile summaries cannot be merged exactly; raw bucket
        counts can, bucket-wise)."""
        out: Dict[str, Any] = {"counters": {}, "gauges": {},
                               "histograms": {}}
        for name, m in self._items():
            if isinstance(m, Counter):
                out["counters"][name] = m.value
            elif isinstance(m, Gauge):
                out["gauges"][name] = m.value
            elif isinstance(m, Histogram):
                with m._lock:
                    out["histograms"][name] = {
                        "bounds": list(m.bounds),
                        "counts": list(m._counts),
                        "sum": m._sum,
                        "count": m._count,
                    }
        return out

    def prometheus_text(self) -> str:
        """Prometheus text exposition format (counters, gauges, cumulative
        histogram buckets + _sum/_count)."""
        lines: List[str] = []
        for name, m in self._items():
            pname = _sanitize(name)
            if isinstance(m, Counter):
                lines.append(f"# TYPE {pname} counter")
                if m.help:
                    lines.append(f"# HELP {pname} {m.help}")
                lines.append(f"{pname} {m.value}")
            elif isinstance(m, Gauge):
                lines.append(f"# TYPE {pname} gauge")
                if m.help:
                    lines.append(f"# HELP {pname} {m.help}")
                v = m.value
                lines.append(f"{pname} {'NaN' if math.isnan(v) else v}")
            elif isinstance(m, Histogram):
                lines.append(f"# TYPE {pname} histogram")
                if m.help:
                    lines.append(f"# HELP {pname} {m.help}")
                cum = 0
                for b, c in zip(m.bounds, m._counts):
                    cum += c
                    lines.append(f'{pname}_bucket{{le="{b}"}} {cum}')
                lines.append(f'{pname}_bucket{{le="+Inf"}} {m.count}')
                lines.append(f"{pname}_sum {m.sum}")
                lines.append(f"{pname}_count {m.count}")
        return "\n".join(lines) + ("\n" if lines else "")
