"""Declarative SLOs + multi-window burn-rate alerting + scenario grading.

The telemetry plane already carries everything an operator needs to judge
the serving fleet — TTFT and per-token-decode histograms, shed/request
counters, ``fleet/scale_up_latency_s`` — but judging was manual: stare at
``latency_summary()`` and decide. This module makes the judgment a
DECLARED artifact:

- :class:`SLOSpec` / :class:`Objective` — objectives over a merged metric
  dump (:meth:`TelemetryAggregator.merged_dump` or a single registry's
  ``dump()``), YAML-loadable (``configs/slo/*.yaml``) so the SLO a fleet
  is graded against ships as reviewable config, not code.
- :class:`SLOEvaluator` — continuous evaluation with **multi-window
  burn-rate alerting** (the Google SRE workbook shape): an alert fires
  only when BOTH a fast and a slow window burn error budget faster than
  ``burn_threshold``, and clears when the fast window recovers — the fast
  window gives detection latency, the slow window kills flappy one-tick
  blips. Transitions (not states) are emitted: a forced — always-sampled,
  the tracer's anomaly contract — ``slo.alert`` span plus a structured
  ``slo_alert`` JSONL event per fire/clear.
- :meth:`SLOEvaluator.grade` — one scored report per scenario run:
  per-objective attainment over the whole window, pass/fail, a 0-100
  score, and the alert history. ``BENCH_MODE=traffic`` emits exactly one
  of these per scenario (``bench.py``).

Exactness contract: error fractions come from histogram BUCKET-COUNT
deltas, which are exact if and only if the objective threshold sits on a
bucket edge. That is why :meth:`MetricsRegistry.configure_buckets`
exists — fleets align bucket bounds with their SLO thresholds (and the
aggregator's :class:`TelemetrySchemaError` guarantees every pod agrees).
An off-edge threshold still works — linear interpolation inside the
containing bucket, same convention as ``Histogram.percentile`` — but the
evaluator says so once (``warn_once``) rather than silently degrading.
"""

from __future__ import annotations

import bisect
import dataclasses
import json
import time
from collections import deque
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

from agilerl_tpu import observability

#: spec schema version (bump on layout changes)
SLO_SCHEMA = 1

_KINDS = ("latency", "ratio", "counter_ceiling")


@dataclasses.dataclass
class Objective:
    """One service-level objective over the merged metric dump.

    - ``kind="latency"`` — at least ``target`` of the observations in
      ``histogram`` must be ≤ ``threshold`` (error budget = 1 - target).
      The canonical fleet objectives: p95 TTFT, per-token decode time,
      scale-up latency.
    - ``kind="ratio"`` — ``numerator`` counter over ``denominator``
      counter must stay ≤ ``budget`` (e.g. shed rate:
      ``serving/shed_requests_total`` / ``serving/requests_total``).
    - ``kind="counter_ceiling"`` — ``counter``'s growth over the graded
      window must stay ≤ ``ceiling`` (e.g. rebalanced requests). Graded,
      never burn-rate alerted: a ceiling has no event-rate denominator to
      burn against.
    """

    name: str
    kind: str = "latency"
    # latency
    histogram: Optional[str] = None
    threshold: Optional[float] = None
    target: float = 0.95
    # ratio
    numerator: Optional[str] = None
    denominator: Optional[str] = None
    budget: Optional[float] = None
    # counter_ceiling
    counter: Optional[str] = None
    ceiling: Optional[float] = None
    #: burn-rate alerting on/off for this objective (latency/ratio only)
    alert: bool = True

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(
                f"objective {self.name!r}: unknown kind {self.kind!r} "
                f"(one of {_KINDS})")
        if self.kind == "latency":
            if self.histogram is None or self.threshold is None:
                raise ValueError(
                    f"latency objective {self.name!r} needs histogram + "
                    "threshold")
            if not 0.0 < self.target < 1.0:
                raise ValueError(
                    f"objective {self.name!r}: target must be in (0, 1)")
        elif self.kind == "ratio":
            if self.numerator is None or self.denominator is None \
                    or self.budget is None:
                raise ValueError(
                    f"ratio objective {self.name!r} needs numerator + "
                    "denominator + budget")
            if not 0.0 < float(self.budget) < 1.0:
                raise ValueError(
                    f"objective {self.name!r}: budget must be in (0, 1)")
        else:
            if self.counter is None or self.ceiling is None:
                raise ValueError(
                    f"counter_ceiling objective {self.name!r} needs "
                    "counter + ceiling")

    @property
    def error_budget(self) -> float:
        """Allowed error fraction (the burn-rate denominator)."""
        if self.kind == "latency":
            return 1.0 - float(self.target)
        if self.kind == "ratio":
            return float(self.budget)
        raise ValueError(f"{self.kind} objectives have no error budget")

    def to_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in dataclasses.asdict(self).items()
                if v is not None}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Objective":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"objective {d.get('name', '<unnamed>')!r}: unknown "
                f"fields {sorted(unknown)}")
        return cls(**d)


@dataclasses.dataclass
class AlertPolicy:
    """Multi-window burn-rate alert shape, shared by every alerting
    objective in a spec. ``burn_threshold`` is the budget-consumption
    multiplier that pages: 1.0 means "exactly on budget"; production specs
    run 2-14x depending on window length (SRE workbook table)."""

    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    burn_threshold: float = 2.0
    #: fewer total events than this in the fast window ⇒ no verdict (a
    #: 1-request window is noise, not a page)
    min_events: int = 5

    def __post_init__(self):
        if self.fast_window_s <= 0 or self.slow_window_s < self.fast_window_s:
            raise ValueError(
                "need 0 < fast_window_s <= slow_window_s "
                f"(got {self.fast_window_s}, {self.slow_window_s})")
        if self.burn_threshold <= 0:
            raise ValueError("burn_threshold must be positive")

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "AlertPolicy":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"alerting: unknown fields {sorted(unknown)}")
        return cls(**d)


@dataclasses.dataclass
class SLOSpec:
    """A named set of objectives + one alert policy — the unit a YAML file
    declares and a scenario is graded against."""

    name: str
    objectives: List[Objective]
    alerting: AlertPolicy = dataclasses.field(default_factory=AlertPolicy)

    def __post_init__(self):
        names = [o.name for o in self.objectives]
        if len(names) != len(set(names)):
            raise ValueError(f"duplicate objective names in spec "
                             f"{self.name!r}: {names}")

    def to_dict(self) -> Dict[str, Any]:
        return {"schema": SLO_SCHEMA, "name": self.name,
                "objectives": [o.to_dict() for o in self.objectives],
                "alerting": self.alerting.to_dict()}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SLOSpec":
        schema = d.get("schema", SLO_SCHEMA)
        if schema != SLO_SCHEMA:
            raise ValueError(f"SLO spec schema {schema} != {SLO_SCHEMA}")
        objs = [Objective.from_dict(o) if not isinstance(o, Objective)
                else o for o in d.get("objectives") or []]
        if not objs:
            raise ValueError(f"SLO spec {d.get('name')!r} has no objectives")
        alerting = d.get("alerting")
        if alerting is None:
            alerting = AlertPolicy()
        elif not isinstance(alerting, AlertPolicy):
            alerting = AlertPolicy.from_dict(alerting)
        return cls(name=str(d.get("name", "slo")), objectives=objs,
                   alerting=alerting)

    def bucket_overrides(self) -> Dict[str, List[float]]:
        """Histogram-name → threshold edges this spec needs for EXACT
        grading — feed into ``ServingFleet(bucket_overrides=...)`` /
        :meth:`MetricsRegistry.configure_buckets` merged with the default
        bounds, so SLO thresholds always sit on bucket edges."""
        out: Dict[str, List[float]] = {}
        for o in self.objectives:
            if o.kind == "latency":
                out.setdefault(o.histogram, []).append(float(o.threshold))
        return {k: sorted(set(v)) for k, v in out.items()}

    def metric_names(self):
        """``(counter_names, histogram_names)`` this spec reads — the
        filters to hand a selective source (``registry_source``,
        ``ServingFleet.merged_dump``) so per-tick evaluation never pays
        for instruments it does not grade."""
        counter_names: List[str] = []
        hist_names: List[str] = []
        for o in self.objectives:
            if o.kind == "latency":
                hist_names.append(o.histogram)
            elif o.kind == "ratio":
                counter_names += [o.numerator, o.denominator]
            else:
                counter_names.append(o.counter)
        return sorted(set(counter_names)), sorted(set(hist_names))

    def apply_buckets(self, registry,
                      base: Optional[Dict[str, Sequence[float]]] = None
                      ) -> Dict[str, List[float]]:
        """Configure ``registry`` so every latency threshold in this spec
        is a bucket edge: per histogram, the union of its existing bounds
        (or ``base[name]`` when the instrument does not exist yet) with the
        spec's thresholds, via :meth:`MetricsRegistry.configure_buckets`.
        Call BEFORE traffic; returns the bounds applied (hand the same
        mapping to ``ServingFleet(bucket_overrides=...)`` so member
        registries agree — the aggregator's exact merge requires it)."""
        applied: Dict[str, List[float]] = {}
        for name, edges in self.bucket_overrides().items():
            cur = (base or {}).get(name) or registry.bucket_bounds(name) or ()
            bounds = aligned_buckets(cur, edges)
            registry.configure_buckets(name, bounds)
            applied[name] = bounds
        return applied


def load_slo_spec(path: Union[str, Path]) -> SLOSpec:
    """Load an :class:`SLOSpec` from YAML (``configs/slo/*.yaml``)."""
    import yaml

    with open(path, encoding="utf-8") as fh:
        d = yaml.safe_load(fh)
    if not isinstance(d, dict):
        raise ValueError(f"{path}: SLO spec must be a mapping")
    return SLOSpec.from_dict(d)


def save_slo_spec(spec: SLOSpec, path: Union[str, Path]) -> Path:
    """Write a spec back to YAML (round-trips with :func:`load_slo_spec`)."""
    import yaml

    from agilerl_tpu.resilience.atomic import atomic_write_bytes

    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    atomic_write_bytes(
        path, yaml.safe_dump(spec.to_dict(), sort_keys=False).encode())
    return path


def aligned_buckets(base: Sequence[float],
                    thresholds: Sequence[float]) -> List[float]:
    """Union of default bucket bounds and SLO thresholds — the bounds a
    fleet should configure so grading is exact AND percentiles keep their
    usual resolution."""
    return sorted({float(b) for b in base} | {float(t) for t in thresholds})


def registry_source(registry, spec: SLOSpec) -> Callable[[], Dict[str, Any]]:
    """A per-tick source that reads ONLY the instruments ``spec`` grades —
    the hot-path alternative to ``registry.dump`` for in-process continuous
    evaluation. A fleet registry carries dozens of instruments; dumping all
    of them every scheduler step is where an evaluator's overhead budget
    (~1%, measured by ``BENCH_MODE=traffic``) actually goes. Reads live
    instrument state directly (same package-internal access the telemetry
    aggregator's materializer uses)."""
    from agilerl_tpu.observability.registry import Counter, Histogram

    counter_names, hist_names = spec.metric_names()

    def read() -> Dict[str, Any]:
        counters: Dict[str, float] = {}
        histograms: Dict[str, Any] = {}
        for n in counter_names:
            m = registry._metrics.get(n)
            if isinstance(m, Counter):
                counters[n] = m.value
        for n in hist_names:
            m = registry._metrics.get(n)
            if isinstance(m, Histogram):
                with m._lock:
                    histograms[n] = {"bounds": m.bounds,
                                     "counts": list(m._counts),
                                     "sum": m._sum, "count": m._count}
        return {"counters": counters, "gauges": {},
                "histograms": histograms}

    return read


# --------------------------------------------------------------------------- #
# evaluation
# --------------------------------------------------------------------------- #

def _hist_errors(h: Dict[str, Any], threshold: float):
    """(errors_above_threshold, total, exact) from one histogram dump.

    Exact when ``threshold`` is a bucket edge (counts[i] holds
    observations in (bounds[i-1], bounds[i]] — everything after the edge's
    bucket is strictly above it); otherwise linearly interpolated inside
    the containing bucket, flagged ``exact=False``."""
    bounds = [float(b) for b in h["bounds"]]
    counts = [int(c) for c in h["counts"]]
    total = int(h["count"])
    i = bisect.bisect_left(bounds, float(threshold))
    if i < len(bounds) and bounds[i] == float(threshold):
        return sum(counts[i + 1:]), total, True
    if i >= len(bounds):  # above the largest finite bound: only overflow
        return counts[-1], total, True
    lo = 0.0 if i == 0 else bounds[i - 1]
    hi = bounds[i]
    frac_above = (hi - float(threshold)) / (hi - lo) if hi > lo else 0.0
    errors = counts[i] * frac_above + sum(counts[i + 1:])
    return errors, total, False


class SLOEvaluator:
    """Continuous SLO evaluation over a metric-dump source.

    ``source`` is any zero-arg callable returning a ``registry.dump()``-
    shaped mapping — typically ``lambda: (agg.poll(), agg.merged_dump())[1]``
    for the cross-process plane, or ``fleet.metrics.dump`` in-process.
    ``clock`` is injectable (tests drive a fake clock; the traffic driver
    drives VIRTUAL time so burn windows are deterministic).

    :meth:`evaluate` is the tick: pull a snapshot, update every alerting
    objective's fast/slow-window burn rates, and emit fire/clear
    TRANSITIONS only — an alert that stays red across ten evaluations
    produces one forced span and one event, not ten (no-flap contract,
    ``tests/test_observability/test_slo.py``). Cost per tick is a dict
    walk over the dump — no I/O, no materialized registry — so running it
    every scheduler step stays inside the ~1% overhead budget the traffic
    bench measures."""

    def __init__(self, spec: SLOSpec,
                 source: Callable[[], Dict[str, Any]], *,
                 clock: Callable[[], float] = time.time,
                 metrics=None, tracer=None):
        self.spec = spec
        self.source = source
        self.clock = clock
        self.metrics = (metrics if metrics is not None
                        else observability.get_registry())
        self._tracer = tracer
        keep_s = spec.alerting.slow_window_s
        #: (ts, {objective: (errors, total)}) ring, pruned past slow window
        self._series: deque = deque()
        self._keep_s = float(keep_s)
        self._firing: Dict[str, bool] = {
            o.name: False for o in spec.objectives}
        self._history: List[Dict[str, Any]] = []
        self._first: Optional[Dict[str, Any]] = None
        self._last: Optional[Dict[str, Any]] = None
        self._first_ts: Optional[float] = None
        self._last_ts: Optional[float] = None

    @property
    def tracer(self):
        return (self._tracer if self._tracer is not None
                else observability.get_tracer())

    # -- reading one dump --------------------------------------------------
    def _measure(self, obj: Objective, dump: Dict[str, Any]):
        """Cumulative (errors, total) for one objective from one dump."""
        if obj.kind == "latency":
            h = (dump.get("histograms") or {}).get(obj.histogram)
            if h is None:
                return 0.0, 0.0
            errors, total, exact = _hist_errors(h, obj.threshold)
            if not exact:
                self.metrics.warn_once(
                    f"slo-threshold-off-grid:{obj.name}",
                    f"SLO objective {obj.name!r}: threshold "
                    f"{obj.threshold} is not a bucket edge of "
                    f"{obj.histogram} — error counts are interpolated, "
                    "not exact; align bounds via "
                    "MetricsRegistry.configure_buckets / "
                    "ServingFleet(bucket_overrides=...)")
            return float(errors), float(total)
        counters = dump.get("counters") or {}
        if obj.kind == "ratio":
            return (float(counters.get(obj.numerator, 0.0)),
                    float(counters.get(obj.denominator, 0.0)))
        return float(counters.get(obj.counter, 0.0)), 0.0

    def _window_fraction(self, name: str, window_s: float, now: float):
        """(error_fraction, events) over the trailing window, from
        cumulative deltas between now and the snapshot at the window
        start. Windows with no new events return (0, 0): no traffic burns
        no budget."""
        cur = self._series[-1][1].get(name)
        ref = None
        for ts, states in self._series:
            if ts <= now - window_s:
                ref = states.get(name)
            else:
                break
        if ref is None:
            if len(self._series) < 2:
                # a single snapshot carries no delta: everything before
                # the evaluator started is out of scope, not a burn
                return 0.0, 0.0
            # window extends past recorded history: burn against the
            # oldest snapshot we have (startup transient, vanishes once
            # the series covers the window)
            ref = self._series[0][1].get(name)
        d_err = max(0.0, cur[0] - ref[0])
        d_tot = max(0.0, cur[1] - ref[1])
        if d_tot <= 0.0:
            return 0.0, 0.0
        return d_err / d_tot, d_tot

    def _transition(self, obj: Objective, phase: str,
                    fast: tuple, slow: tuple, now: float) -> None:
        fields = {
            "objective": obj.name, "phase": phase, "spec": self.spec.name,
            "burn_fast": round(fast[0], 6), "burn_slow": round(slow[0], 6),
            "events_fast": fast[1], "events_slow": slow[1],
            "burn_threshold": self.spec.alerting.burn_threshold,
            "error_budget": obj.error_budget, "at_s": now,
        }
        self._history.append(dict(fields))
        self.metrics.counter(
            f"slo/alerts_{'fired' if phase == 'fire' else 'cleared'}_total",
            help="SLO burn-rate alert transitions").inc()
        self.metrics.emit("slo_alert", **fields)
        tr = self.tracer
        if tr is not None and getattr(tr, "enabled", False):
            # forced span: an SLO transition is an anomaly — always
            # sampled regardless of trace sampling, error status on fire
            span = tr.start_span(f"slo.{phase}", force=True,
                                 attributes=fields)
            if phase == "fire":
                span.set_error(f"{obj.name} burning "
                               f"{fast[0]:.1f}x budget")
            span.end()

    # -- the tick ----------------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One evaluation tick. Returns the per-objective state map
        ``{name: {burn_fast, burn_slow, firing, ...}}`` (alert TRANSITIONS
        additionally emit spans/events — see the class docstring)."""
        now = float(self.clock()) if now is None else float(now)
        dump = self.source()
        states = {o.name: self._measure(o, dump)
                  for o in self.spec.objectives}
        self._series.append((now, states))
        while (len(self._series) > 2
               and self._series[1][0] <= now - self._keep_s):
            self._series.popleft()
        if self._first is None:
            self._first, self._first_ts = dump, now
        self._last, self._last_ts = dump, now
        pol = self.spec.alerting
        out: Dict[str, Any] = {}
        for obj in self.spec.objectives:
            if obj.kind == "counter_ceiling" or not obj.alert:
                continue
            fast_f, fast_n = self._window_fraction(
                obj.name, pol.fast_window_s, now)
            slow_f, slow_n = self._window_fraction(
                obj.name, pol.slow_window_s, now)
            budget = obj.error_budget
            fast = (fast_f / budget, fast_n)
            slow = (slow_f / budget, slow_n)
            firing = self._firing[obj.name]
            if not firing:
                if (fast_n >= pol.min_events
                        and fast[0] >= pol.burn_threshold
                        and slow[0] >= pol.burn_threshold):
                    self._firing[obj.name] = True
                    self._transition(obj, "fire", fast, slow, now)
            elif fast[0] < pol.burn_threshold:
                # clear on fast-window recovery: the slow window keeps the
                # historical burn for a while by construction, and waiting
                # it out would hold a resolved page open for minutes
                self._firing[obj.name] = False
                self._transition(obj, "clear", fast, slow, now)
            out[obj.name] = {
                "burn_fast": fast[0], "burn_slow": slow[0],
                "events_fast": fast[1], "events_slow": slow[1],
                "firing": self._firing[obj.name],
            }
        return out

    @property
    def active_alerts(self) -> List[str]:
        return sorted(n for n, f in self._firing.items() if f)

    @property
    def alert_history(self) -> List[Dict[str, Any]]:
        """Every fire/clear transition this evaluator emitted."""
        return list(self._history)

    # -- grading -----------------------------------------------------------
    def grade(self, scenario: Optional[str] = None,
              extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """One scored report over everything seen between the first and
        last :meth:`evaluate` — the per-scenario JSON ``BENCH_MODE=traffic``
        emits. Attainment is computed from cumulative deltas over the full
        run, so a scenario is graded on ALL of its traffic, not on
        whichever alert window happened to be open at the end."""
        if self._first is None:
            raise RuntimeError("grade() before any evaluate() tick")
        objectives = []
        passed = 0
        gradeable = 0
        for obj in self.spec.objectives:
            e0, t0 = self._measure(obj, self._first)
            e1, t1 = self._measure(obj, self._last)
            d_err, d_tot = max(0.0, e1 - e0), max(0.0, t1 - t0)
            row: Dict[str, Any] = {"name": obj.name, "kind": obj.kind}
            if obj.kind == "counter_ceiling":
                row.update(counter=obj.counter, ceiling=obj.ceiling,
                           value=d_err, ok=d_err <= float(obj.ceiling))
            elif d_tot <= 0.0:
                # no traffic reached this objective: vacuous pass, but say
                # so — a scenario that never exercised an objective should
                # not read as evidence the objective holds
                row.update(value=None, ok=True, no_data=True,
                           error_budget=obj.error_budget)
            else:
                frac = d_err / d_tot
                row.update(
                    attained=round(1.0 - frac, 6),
                    error_fraction=round(frac, 6),
                    error_budget=obj.error_budget,
                    events=d_tot,
                    budget_consumed=round(frac / obj.error_budget, 4),
                    # tolerance: error_budget = 1 - target is already one
                    # float subtraction away from exact; landing precisely
                    # ON budget must grade as met
                    ok=frac <= obj.error_budget + 1e-9,
                )
                if obj.kind == "latency":
                    row.update(histogram=obj.histogram,
                               threshold=obj.threshold, target=obj.target)
                else:
                    row.update(numerator=obj.numerator,
                               denominator=obj.denominator)
            if obj.alert:
                row["alerts"] = sum(
                    1 for h in self._history
                    if h["objective"] == obj.name and h["phase"] == "fire")
            objectives.append(row)
            gradeable += 1
            passed += bool(row["ok"])
        score = round(100.0 * passed / max(1, gradeable), 1)
        report = {
            "spec": self.spec.name,
            "scenario": scenario,
            "objectives": objectives,
            "passed": passed,
            "total": gradeable,
            "score": score,
            "ok": passed == gradeable,
            "alerts": self.alert_history,
            "active_alerts": self.active_alerts,
            "window_s": (round(self._last_ts - self._first_ts, 6)
                         if self._last_ts is not None else 0.0),
            "evaluations": len(self._series),
        }
        if extra:
            report.update(extra)
        return report


def attribute_scale_ups(events: Sequence[Dict[str, Any]]
                        ) -> List[Dict[str, Any]]:
    """Join the event stream into alert→reaction attribution records: for
    each ``slo_alert`` fire, the first ACTUATED scale-up
    ``autoscale_decision`` that follows it (by event order — both streams
    share one sink, so sink sequence IS causal order within a process),
    and the alert's clear if one followed. The per-incident record a
    degraded-run grade embeds: which breach paged, what the autoscaler saw
    when it reacted, and whether the page closed."""
    out: List[Dict[str, Any]] = []
    open_incident: Optional[Dict[str, Any]] = None
    for ev in events:
        kind = ev.get("kind")
        if kind == "slo_alert" and ev.get("phase") == "fire":
            open_incident = {
                "objective": ev.get("objective"),
                "fired_at_s": ev.get("at_s"),
                "burn_fast": ev.get("burn_fast"),
                "scale_up": None,
                "cleared_at_s": None,
            }
            out.append(open_incident)
        elif open_incident is not None:
            if (kind == "autoscale_decision" and ev.get("actioned")
                    and ev.get("verdict") == "up"
                    and open_incident["scale_up"] is None):
                open_incident["scale_up"] = {
                    "replica": ev.get("replica"),
                    "triggers": ev.get("triggers"),
                    "signals": ev.get("signals"),
                }
            elif (kind == "slo_alert" and ev.get("phase") == "clear"
                    and ev.get("objective") == open_incident["objective"]):
                open_incident["cleared_at_s"] = ev.get("at_s")
                open_incident = None
    return out


def write_report(report: Dict[str, Any], path: Union[str, Path]) -> Path:
    """Persist one scored report as JSON, atomically (a crashed bench must
    not leave a truncated report a dashboard later trusts)."""
    from agilerl_tpu.resilience.atomic import atomic_write_bytes

    path = Path(path)
    atomic_write_bytes(
        path, (json.dumps(report, indent=2, sort_keys=True) + "\n").encode())
    return path
