"""Structured JSONL event sink.

One event per line: ``{"seq": N, "ts": unix_seconds, "kind": ..., **payload}``.
``seq`` is a per-sink monotone index — consumers (the evo-PPO smoke test,
``bench.py`` timeline readers) sort/validate on it rather than wall time,
which can repeat at millisecond granularity.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional


def _jsonable(v: Any) -> Any:
    """Best-effort coercion for numpy/jax scalars and arrays."""
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    item = getattr(v, "item", None)
    if callable(item):
        try:
            return item()
        except Exception:
            pass
    tolist = getattr(v, "tolist", None)
    if callable(tolist):
        try:
            return tolist()
        except Exception:
            pass
    return repr(v)


def _resume_seq(path: str) -> int:
    """Continue the monotone ``seq`` past an existing file's last event —
    appending a second run must not restart at 0 (consumers order on seq).

    The FINAL line may be torn (a crash mid-write leaves a truncated tail;
    append-mode JSONL tolerates that by design), so the scan walks
    BACKWARDS through the tail window to the last *parseable* event — a
    torn tail must not reset seq to 0 and break the monotone contract."""
    try:
        with open(path, "rb") as fh:
            fh.seek(0, 2)
            size = fh.tell()
            if size == 0:
                return 0
            fh.seek(max(0, size - 65536))
            lines = fh.read().splitlines()
        for last in reversed(lines):
            try:
                return int(json.loads(last)["seq"]) + 1
            except (ValueError, KeyError, TypeError):
                continue
        return 0
    except (OSError, ValueError, KeyError, IndexError, TypeError):
        return 0


class JsonlSink:
    """Append structured events to a JSONL file, flushing per line so a
    crashed run still leaves a readable timeline."""

    def __init__(self, path: str):
        self.path = str(path)
        self._seq = _resume_seq(self.path)
        self._fh = open(self.path, "a", encoding="utf-8")
        # a torn final line (no trailing newline — crash mid-write) must
        # not absorb the first appended record into its garbage: resume
        # appending on a fresh line
        try:
            with open(self.path, "rb") as fh:
                fh.seek(0, 2)
                if fh.tell() > 0:
                    fh.seek(-1, 2)
                    if fh.read(1) != b"\n":
                        self._fh.write("\n")
        except OSError:  # pragma: no cover - exotic filesystems
            pass
        self._lock = threading.Lock()

    def emit(self, kind: str, payload: Dict[str, Any]) -> None:
        record = {"seq": None, "ts": round(time.time(), 6), "kind": str(kind)}
        record.update({k: _jsonable(v) for k, v in payload.items()})
        with self._lock:
            if self._fh.closed:
                return  # late event after close(): drop, never crash the run
            record["seq"] = self._seq
            self._seq += 1
            self._fh.write(json.dumps(record) + "\n")
            self._fh.flush()

    @property
    def closed(self) -> bool:
        return self._fh.closed

    def flush(self) -> None:
        """Flush + best-effort fsync — the resilience PreemptionGuard calls
        this (from the main thread, at the first step boundary after a
        preemption signal) so the timeline is durable even when the grace
        window expires before the final snapshot."""
        with self._lock:
            if self._fh.closed:
                return
            self._fh.flush()
            try:
                os.fsync(self._fh.fileno())
            except OSError:  # pragma: no cover - exotic filesystems
                pass

    def close(self) -> None:
        with self._lock:
            if not self._fh.closed:
                self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class MemorySink:
    """In-process sink for tests and interactive inspection."""

    def __init__(self):
        self.events: List[Dict[str, Any]] = []
        self._seq = 0
        self._lock = threading.Lock()

    def emit(self, kind: str, payload: Dict[str, Any]) -> None:
        record = {"seq": None, "ts": round(time.time(), 6), "kind": str(kind)}
        record.update({k: _jsonable(v) for k, v in payload.items()})
        with self._lock:
            record["seq"] = self._seq
            self._seq += 1
            self.events.append(record)

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class NullSink:
    """Discard everything (the default when telemetry is not configured)."""

    def emit(self, kind: str, payload: Dict[str, Any]) -> None:
        pass

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


def read_jsonl(path: str) -> List[Dict[str, Any]]:
    """Load a JSONL event file, skipping blank AND unparseable lines.

    Torn lines are possible BY DESIGN (a crash mid-write truncates the
    tail; the restarted sink keeps it and appends on a fresh line), so the
    post-crash reconstruction workflow — ``span_records(read_jsonl(...))``
    → ``export_perfetto`` — must read past them, not raise on the exact
    file the crash tooling exists for. Every parseable event is returned."""
    out = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue  # torn/garbage line: tolerated by design
    return out
