"""Evolvable LSTM encoder (parity: agilerl/modules/lstm.py — EvolvableLSTM:11,
mutations :239-280, hidden_state_architecture:94 for recurrent PPO).

TPU-first: the recurrence runs as lax.scan over time; multi-layer stacks scan
layer-by-layer (static depth). Hidden state is an explicit pytree the caller
threads, never hidden module state.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from agilerl_tpu.modules import layers as L
from agilerl_tpu.modules.base import EvolvableModule, config_replace, mutation
from agilerl_tpu.typing import MutationType
from agilerl_tpu.utils.rng import derive_rng
from agilerl_tpu.utils.rng import derive_key


@dataclasses.dataclass(frozen=True)
class LSTMConfig:
    num_inputs: int
    num_outputs: int
    hidden_size: int = 64
    num_layers: int = 1
    min_hidden_size: int = 16
    max_hidden_size: int = 500
    min_layers: int = 1
    max_layers: int = 3
    output_activation: Optional[str] = None

    def __post_init__(self):
        assert self.num_inputs > 0 and self.num_outputs > 0
        assert self.min_layers <= self.num_layers <= self.max_layers


class EvolvableLSTM(EvolvableModule):
    Config = LSTMConfig

    def __init__(
        self,
        num_inputs: Optional[int] = None,
        num_outputs: Optional[int] = None,
        key: Optional[jax.Array] = None,
        config: Optional[LSTMConfig] = None,
        **kwargs,
    ):
        if config is None:
            config = LSTMConfig(num_inputs=num_inputs, num_outputs=num_outputs, **kwargs)
        if key is None:
            key = derive_key()
        super().__init__(config, key)

    @staticmethod
    def init_params(key: jax.Array, config: LSTMConfig) -> Dict:
        params: Dict = {}
        keys = jax.random.split(key, config.num_layers + 1)
        in_dim = config.num_inputs
        for i in range(config.num_layers):
            params[f"lstm_{i}"] = L.lstm_cell_init(keys[i], in_dim, config.hidden_size)
            in_dim = config.hidden_size
        params["output"] = L.dense_init(keys[-1], config.hidden_size, config.num_outputs)
        return params

    @staticmethod
    def initial_hidden(config: LSTMConfig, batch: int) -> Dict[str, jax.Array]:
        """Zero hidden state pytree (parity: hidden_state_architecture, lstm.py:94)."""
        return {
            "h": jnp.zeros((config.num_layers, batch, config.hidden_size)),
            "c": jnp.zeros((config.num_layers, batch, config.hidden_size)),
        }

    @staticmethod
    def apply(
        config: LSTMConfig,
        params: Dict,
        x: jax.Array,
        hidden: Optional[Dict[str, jax.Array]] = None,
        return_hidden: bool = False,
        **_,
    ):
        """x: [B, D] single step or [T, B, D] sequence. Returns output at final
        timestep (and new hidden state if return_hidden)."""
        single_step = x.ndim == 2
        if single_step:
            x = x[None]
        batch = x.shape[1]
        if hidden is None:
            hidden = EvolvableLSTM.initial_hidden(config, batch)
        hs, cs = [], []
        seq = x.astype(jnp.float32)
        for i in range(config.num_layers):
            seq, (h, c) = L.lstm_scan(params[f"lstm_{i}"], seq, hidden["h"][i], hidden["c"][i])
            hs.append(h)
            cs.append(c)
        out = L.dense_apply(params["output"], seq[-1])
        out_act = L.get_activation(config.output_activation)
        out = out_act(out)
        if return_hidden:
            return out, {"h": jnp.stack(hs), "c": jnp.stack(cs)}
        return out

    # -- mutations ------------------------------------------------------ #
    @mutation(MutationType.LAYER)
    def add_layer(self, rng: Optional[np.random.Generator] = None) -> Dict:
        cfg = self.config
        if cfg.num_layers >= cfg.max_layers:
            return self.add_node(rng=rng)
        self._morph(config_replace(cfg, num_layers=cfg.num_layers + 1))
        return {}

    @mutation(MutationType.LAYER, shrink_params=True)
    def remove_layer(self, rng: Optional[np.random.Generator] = None) -> Dict:
        cfg = self.config
        if cfg.num_layers <= cfg.min_layers:
            return self.add_node(rng=rng)
        self._morph(config_replace(cfg, num_layers=cfg.num_layers - 1))
        return {}

    @mutation(MutationType.NODE)
    def add_node(
        self, numb_new_nodes: Optional[int] = None, rng: Optional[np.random.Generator] = None
    ) -> Dict:
        rng = derive_rng(rng)
        if numb_new_nodes is None:
            numb_new_nodes = int(rng.choice([16, 32, 64]))
        cfg = self.config
        new = min(cfg.hidden_size + numb_new_nodes, cfg.max_hidden_size)
        self._morph(config_replace(cfg, hidden_size=new))
        return {"numb_new_nodes": numb_new_nodes}

    @mutation(MutationType.NODE, shrink_params=True)
    def remove_node(
        self, numb_new_nodes: Optional[int] = None, rng: Optional[np.random.Generator] = None
    ) -> Dict:
        rng = derive_rng(rng)
        if numb_new_nodes is None:
            numb_new_nodes = int(rng.choice([16, 32, 64]))
        cfg = self.config
        new = max(cfg.hidden_size - numb_new_nodes, cfg.min_hidden_size)
        self._morph(config_replace(cfg, hidden_size=new))
        return {"numb_new_nodes": numb_new_nodes}
