"""DummyEvolvable (parity: agilerl/modules/dummy.py — DummyEvolvable:19): wraps
an arbitrary (config, init_fn, apply_fn) triple into the EvolvableModule
interface with NO mutation methods, so non-evolvable nets (e.g. frozen
pretrained encoders) slot into algorithms unchanged.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import numpy as np

from agilerl_tpu.modules.base import EvolvableModule
from agilerl_tpu.utils.rng import derive_key


class DummyEvolvable(EvolvableModule):
    def __init__(
        self,
        init_fn: Callable[[jax.Array], Any],
        apply_fn: Callable[..., Any],
        config: Any = None,
        key: Optional[jax.Array] = None,
    ):
        self._init_fn = init_fn
        self._apply_fn = apply_fn
        if key is None:
            key = derive_key()
        super().__init__(config, key)

    def init_params(self, key, config):  # type: ignore[override]
        return self._init_fn(key)

    def apply(self, config, params, x, **kw):  # type: ignore[override]
        return self._apply_fn(params, x, **kw)

    def __call__(self, x, **kw):
        return self._apply_fn(self.params, x, **kw)

    @classmethod
    def get_mutation_methods(cls):
        return {}

    def sample_mutation_method(self, new_layer_prob=0.2, rng=None):
        raise ValueError("DummyEvolvable has no mutation methods")
