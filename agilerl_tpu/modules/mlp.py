"""Evolvable MLP (parity: agilerl/modules/mlp.py — EvolvableMLP:10, mutations
add_layer:228, remove_layer:242, add_node:255, remove_node:285).

TPU-first notes: the whole net is a pure function of a frozen config; a node/layer
mutation builds a new config and re-uses every overlapping weight slab (see
modules/base.py preserve_params). Dense widths are kept free — XLA pads onto MXU
tiles; population benchmarks should prefer multiples of 128 via net-config choice.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from agilerl_tpu.modules import layers as L
from agilerl_tpu.modules.base import EvolvableModule, config_replace, mutation, tuple_set
from agilerl_tpu.typing import MutationType
from agilerl_tpu.utils.rng import derive_rng
from agilerl_tpu.utils.rng import derive_key


@dataclasses.dataclass(frozen=True)
class MLPConfig:
    num_inputs: int
    num_outputs: int
    hidden_size: Tuple[int, ...] = (64, 64)
    activation: str = "ReLU"
    output_activation: Optional[str] = None
    min_hidden_layers: int = 1
    max_hidden_layers: int = 3
    min_mlp_nodes: int = 64
    max_mlp_nodes: int = 500
    layer_norm: bool = True
    output_layernorm: bool = False
    output_vanish: bool = True
    init_layers: bool = True
    noisy: bool = False
    noise_std: float = 0.5

    def __post_init__(self):
        assert len(self.hidden_size) >= 1, "MLP needs at least one hidden layer"
        assert self.num_inputs > 0 and self.num_outputs > 0


class EvolvableMLP(EvolvableModule):
    Config = MLPConfig

    def __init__(
        self,
        num_inputs: Optional[int] = None,
        num_outputs: Optional[int] = None,
        key: Optional[jax.Array] = None,
        config: Optional[MLPConfig] = None,
        **kwargs,
    ):
        if config is None:
            config = MLPConfig(num_inputs=num_inputs, num_outputs=num_outputs, **kwargs)
        if key is None:
            key = derive_key()
        super().__init__(config, key)

    # ------------------------------------------------------------------ #
    @staticmethod
    def init_params(key: jax.Array, config: MLPConfig) -> Dict:
        sizes = (config.num_inputs,) + config.hidden_size
        params: Dict = {}
        keys = jax.random.split(key, len(config.hidden_size) + 1)
        make = L.noisy_dense_init if config.noisy else L.dense_init
        if config.noisy:
            make = lambda k, i, o: L.noisy_dense_init(k, i, o, config.noise_std)  # noqa: E731
        for i in range(len(config.hidden_size)):
            params[f"layer_{i}"] = make(keys[i], sizes[i], sizes[i + 1])
            if config.layer_norm:
                params[f"norm_{i}"] = L.layer_norm_init(sizes[i + 1])
        out = make(keys[-1], sizes[-1], config.num_outputs)
        if config.output_vanish and not config.noisy:
            out = {k: v * 0.1 for k, v in out.items()}
        params["output"] = out
        if config.output_layernorm:
            params["norm_out"] = L.layer_norm_init(config.num_outputs)
        return params

    @staticmethod
    def apply(
        config: MLPConfig,
        params: Dict,
        x: jax.Array,
        key: Optional[jax.Array] = None,
        **_,
    ) -> jax.Array:
        act = L.get_activation(config.activation)
        out_act = L.get_activation(config.output_activation)
        n = len(config.hidden_size)
        if config.noisy:
            keys = (
                jax.random.split(key, n + 1) if key is not None else [None] * (n + 1)
            )
            dense = L.noisy_dense_apply
        else:
            keys = [None] * (n + 1)
            dense = lambda p, h, k: L.dense_apply(p, h)  # noqa: E731
        h = x.astype(jnp.float32)
        for i in range(n):
            h = (
                dense(params[f"layer_{i}"], h, keys[i])
                if config.noisy
                else L.dense_apply(params[f"layer_{i}"], h)
            )
            if config.layer_norm:
                h = L.layer_norm_apply(params[f"norm_{i}"], h)
            h = act(h)
        h = (
            dense(params["output"], h, keys[-1])
            if config.noisy
            else L.dense_apply(params["output"], h)
        )
        if config.output_layernorm:
            h = L.layer_norm_apply(params["norm_out"], h)
        return out_act(h)

    # -- mutations ------------------------------------------------------ #
    @mutation(MutationType.LAYER)
    def add_layer(self, rng: Optional[np.random.Generator] = None) -> Dict:
        """Append a hidden layer (parity: mlp.py:228)."""
        cfg = self.config
        if len(cfg.hidden_size) >= cfg.max_hidden_layers:
            return self.add_node(rng=rng)
        new_hidden = cfg.hidden_size + (cfg.hidden_size[-1],)
        self._morph(config_replace(cfg, hidden_size=new_hidden))
        return {}

    @mutation(MutationType.LAYER, shrink_params=True)
    def remove_layer(self, rng: Optional[np.random.Generator] = None) -> Dict:
        """Drop the last hidden layer (parity: mlp.py:242)."""
        cfg = self.config
        if len(cfg.hidden_size) <= cfg.min_hidden_layers:
            return self.add_node(rng=rng)
        self._morph(config_replace(cfg, hidden_size=cfg.hidden_size[:-1]))
        return {}

    @mutation(MutationType.NODE)
    def add_node(
        self,
        hidden_layer: Optional[int] = None,
        numb_new_nodes: Optional[int] = None,
        rng: Optional[np.random.Generator] = None,
    ) -> Dict:
        """Grow a random hidden layer by {16,32,64} nodes (parity: mlp.py:255)."""
        rng = derive_rng(rng)
        cfg = self.config
        if hidden_layer is None:
            hidden_layer = int(rng.integers(0, len(cfg.hidden_size)))
        hidden_layer = min(hidden_layer, len(cfg.hidden_size) - 1)
        if numb_new_nodes is None:
            numb_new_nodes = int(rng.choice([16, 32, 64]))
        new_size = min(cfg.hidden_size[hidden_layer] + numb_new_nodes, cfg.max_mlp_nodes)
        self._morph(
            config_replace(cfg, hidden_size=tuple_set(cfg.hidden_size, hidden_layer, new_size))
        )
        return {"hidden_layer": hidden_layer, "numb_new_nodes": numb_new_nodes}

    @mutation(MutationType.NODE, shrink_params=True)
    def remove_node(
        self,
        hidden_layer: Optional[int] = None,
        numb_new_nodes: Optional[int] = None,
        rng: Optional[np.random.Generator] = None,
    ) -> Dict:
        """Shrink a random hidden layer (parity: mlp.py:285)."""
        rng = derive_rng(rng)
        cfg = self.config
        if hidden_layer is None:
            hidden_layer = int(rng.integers(0, len(cfg.hidden_size)))
        hidden_layer = min(hidden_layer, len(cfg.hidden_size) - 1)
        if numb_new_nodes is None:
            numb_new_nodes = int(rng.choice([16, 32, 64]))
        new_size = max(cfg.hidden_size[hidden_layer] - numb_new_nodes, cfg.min_mlp_nodes)
        self._morph(
            config_replace(cfg, hidden_size=tuple_set(cfg.hidden_size, hidden_layer, new_size))
        )
        return {"hidden_layer": hidden_layer, "numb_new_nodes": numb_new_nodes}
