from agilerl_tpu.modules.base import (
    EvolvableModule,
    ModuleDict,
    mutation,
    preserve_params,
)
from agilerl_tpu.modules.mlp import EvolvableMLP, MLPConfig

__all__ = [
    "EvolvableModule",
    "ModuleDict",
    "mutation",
    "preserve_params",
    "EvolvableMLP",
    "MLPConfig",
]
