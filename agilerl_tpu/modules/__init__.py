from agilerl_tpu.modules.base import (
    EvolvableModule,
    ModuleDict,
    mutation,
    preserve_params,
)
from agilerl_tpu.modules.bert import BERTConfig, EvolvableBERT
from agilerl_tpu.modules.cnn import CNNConfig, EvolvableCNN
from agilerl_tpu.modules.dummy import DummyEvolvable
from agilerl_tpu.modules.gpt import EvolvableGPT
from agilerl_tpu.modules.lstm import EvolvableLSTM, LSTMConfig
from agilerl_tpu.modules.mlp import EvolvableMLP, MLPConfig
from agilerl_tpu.modules.multi_input import EvolvableMultiInput, MultiInputConfig
from agilerl_tpu.modules.resnet import EvolvableResNet, ResNetConfig
from agilerl_tpu.modules.simba import EvolvableSimBa, SimBaConfig

__all__ = [
    "EvolvableModule", "ModuleDict", "mutation", "preserve_params",
    "EvolvableMLP", "MLPConfig", "EvolvableCNN", "CNNConfig",
    "EvolvableLSTM", "LSTMConfig", "EvolvableMultiInput", "MultiInputConfig",
    "EvolvableSimBa", "SimBaConfig", "EvolvableResNet", "ResNetConfig",
    "EvolvableGPT", "EvolvableBERT", "BERTConfig", "DummyEvolvable",
]
