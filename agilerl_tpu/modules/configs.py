"""Net-config dataclasses with dict/YAML loading (parity: agilerl/modules/configs.py
— MlpNetConfig:56, SimBaNetConfig:87, CnnNetConfig:114, LstmNetConfig:131,
MultiInputNetConfig:143).

In this framework the per-module architecture configs live next to their modules
(MLPConfig in modules/mlp.py, etc.). This module provides the reference-style
*user-facing* net-config layer: named dataclass aliases plus YAML/dict loaders
that produce the ``net_config`` kwargs accepted by every algorithm
(latent_dim / encoder_config / head_config / simba / recurrent).
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any, Dict, Optional, Union

from agilerl_tpu.modules.cnn import CNNConfig as CnnNetConfig  # noqa: F401
from agilerl_tpu.modules.lstm import LSTMConfig as LstmNetConfig  # noqa: F401
from agilerl_tpu.modules.mlp import MLPConfig as MlpNetConfig  # noqa: F401
from agilerl_tpu.modules.multi_input import (  # noqa: F401
    MultiInputConfig as MultiInputNetConfig,
)
from agilerl_tpu.modules.simba import SimBaConfig as SimBaNetConfig  # noqa: F401

_KNOWN_KEYS = {
    "latent_dim", "encoder_config", "head_config", "simba", "recurrent",
    "min_latent_dim", "max_latent_dim",
}


def load_net_config(source: Union[str, Path, Dict[str, Any], None]) -> Dict[str, Any]:
    """Load a net_config dict from YAML path or dict, normalising keys.

    Accepts the reference's YAML shape (e.g. {"latent_dim": 64,
    "encoder_config": {"hidden_size": [64, 64]}}) and converts lists to the
    tuples the frozen config dataclasses require."""
    if source is None:
        return {}
    if isinstance(source, (str, Path)):
        import yaml

        with open(source) as f:
            source = yaml.safe_load(f) or {}
    out: Dict[str, Any] = {}
    for k, v in source.items():
        key = k.lower()
        if key not in _KNOWN_KEYS:
            continue
        if isinstance(v, dict):
            v = {
                sk: tuple(sv) if isinstance(sv, list) else sv for sk, sv in v.items()
            }
        out[key] = v
    return out


def _tuplify(x):
    """YAML sequences arrive as lists; the frozen net-config dataclasses need
    hashable tuples (they key the jit cache)."""
    if isinstance(x, list):
        return tuple(_tuplify(v) for v in x)
    if isinstance(x, dict):
        return {k: _tuplify(v) for k, v in x.items()}
    return x


def load_yaml_config(path: Union[str, Path]) -> Dict[str, Any]:
    """Load a full training YAML (INIT_HP / MUTATION_PARAMS / NET_CONFIG
    sections, parity with configs/training/*.yaml in the reference)."""
    import yaml

    with open(path) as f:
        cfg = yaml.safe_load(f) or {}
    if "NET_CONFIG" in cfg:
        cfg["NET_CONFIG"] = _tuplify(cfg["NET_CONFIG"])
    if "MODEL" in cfg:
        cfg["MODEL"] = _tuplify(cfg["MODEL"])
    return cfg
