"""EvolvableBERT — evolvable encoder-decoder transformer
(parity: agilerl/modules/bert.py — EvolvableBERT:12 with layer/node mutations
:512-530,536,582).

Compact pre-norm encoder-decoder: bidirectional encoder self-attention, causal
decoder self-attention + cross-attention, GELU MLPs. Blocks are name-keyed so
layer mutations preserve weights; node mutations morph d_model slab-wise.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from agilerl_tpu.modules import layers as L
from agilerl_tpu.modules.base import EvolvableModule, mutation
from agilerl_tpu.typing import MutationType
from agilerl_tpu.utils.rng import derive_rng
from agilerl_tpu.utils.rng import derive_key


@dataclasses.dataclass(frozen=True)
class BERTConfig:
    vocab_size: int
    n_encoder_layers: int = 2
    n_decoder_layers: int = 2
    n_head: int = 4
    d_model: int = 128
    d_ff: Optional[int] = None
    max_seq_len: int = 256

    @property
    def ff_dim(self) -> int:
        return self.d_ff or 4 * self.d_model

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head


def _attn_init(key, d, n_head):
    ks = jax.random.split(key, 4)
    std = 0.02
    return {
        "wq": std * jax.random.normal(ks[0], (d, d)),
        "wk": std * jax.random.normal(ks[1], (d, d)),
        "wv": std * jax.random.normal(ks[2], (d, d)),
        "wo": std * jax.random.normal(ks[3], (d, d)),
    }


def _attn(params, q_in, kv_in, n_head, mask=None):
    B, Tq, D = q_in.shape
    Tk = kv_in.shape[1]
    hd = D // n_head
    q = (q_in @ params["wq"]).reshape(B, Tq, n_head, hd)
    k = (kv_in @ params["wk"]).reshape(B, Tk, n_head, hd)
    v = (kv_in @ params["wv"]).reshape(B, Tk, n_head, hd)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(hd)
    if mask is not None:
        scores = jnp.where(mask, scores, -1e9)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, Tq, D)
    return out @ params["wo"]


def _mlp_init(key, d, ff):
    k1, k2 = jax.random.split(key)
    return {"fc1": L.dense_init(k1, d, ff), "fc2": L.dense_init(k2, ff, d)}


def _mlp(params, x):
    return L.dense_apply(params["fc2"], jax.nn.gelu(L.dense_apply(params["fc1"], x)))


class EvolvableBERT(EvolvableModule):
    Config = BERTConfig

    def __init__(
        self,
        vocab_size: Optional[int] = None,
        key: Optional[jax.Array] = None,
        config: Optional[BERTConfig] = None,
        min_layers: int = 1,
        max_layers: int = 8,
        min_d_model: int = 64,
        max_d_model: int = 1024,
        **kwargs,
    ):
        if config is None:
            config = BERTConfig(vocab_size=vocab_size, **kwargs)
        if key is None:
            key = derive_key()
        self.min_layers = min_layers
        self.max_layers = max_layers
        self.min_d_model = min_d_model
        self.max_d_model = max_d_model
        super().__init__(config, key)

    @staticmethod
    def init_params(key: jax.Array, config: BERTConfig) -> Dict:
        d, ff = config.d_model, config.ff_dim
        keys = jax.random.split(key, 4 + 2 * (config.n_encoder_layers + config.n_decoder_layers))
        params: Dict = {
            "tok_emb": 0.02 * jax.random.normal(keys[0], (config.vocab_size, d)),
            "pos_emb": 0.02 * jax.random.normal(keys[1], (config.max_seq_len, d)),
            "encoder": {},
            "decoder": {},
            "ln_f": L.layer_norm_init(d),
            "lm_head": 0.02 * jax.random.normal(keys[2], (d, config.vocab_size)),
        }
        ki = 3
        for i in range(config.n_encoder_layers):
            params["encoder"][str(i)] = {
                "ln1": L.layer_norm_init(d),
                "attn": _attn_init(keys[ki], d, config.n_head),
                "ln2": L.layer_norm_init(d),
                "mlp": _mlp_init(keys[ki + 1], d, ff),
            }
            ki += 2
        for i in range(config.n_decoder_layers):
            k_extra = jax.random.fold_in(keys[ki], 7)
            params["decoder"][str(i)] = {
                "ln1": L.layer_norm_init(d),
                "self_attn": _attn_init(keys[ki], d, config.n_head),
                "ln_x": L.layer_norm_init(d),
                "cross_attn": _attn_init(k_extra, d, config.n_head),
                "ln2": L.layer_norm_init(d),
                "mlp": _mlp_init(keys[ki + 1], d, ff),
            }
            ki += 2
        return params

    @staticmethod
    def encode(config: BERTConfig, params: Dict, src: jax.Array,
               src_mask: Optional[jax.Array] = None) -> jax.Array:
        B, T = src.shape
        h = jnp.take(params["tok_emb"], src, axis=0) + params["pos_emb"][None, :T]
        mask = None
        if src_mask is not None:
            mask = src_mask[:, None, None, :].astype(bool)
        for i in range(config.n_encoder_layers):
            blk = params["encoder"][str(i)]
            x = L.layer_norm_apply(blk["ln1"], h)
            h = h + _attn(blk["attn"], x, x, config.n_head, mask)
            h = h + _mlp(blk["mlp"], L.layer_norm_apply(blk["ln2"], h))
        return h

    @staticmethod
    def apply(
        config: BERTConfig,
        params: Dict,
        src: jax.Array,
        tgt: Optional[jax.Array] = None,
        src_mask: Optional[jax.Array] = None,
        **_,
    ) -> jax.Array:
        """Encoder-decoder forward: returns decoder logits [B, Tt, V]
        (tgt=None -> encode only, returns encoder states)."""
        enc = EvolvableBERT.encode(config, params, src, src_mask)
        if tgt is None:
            return enc
        B, Tt = tgt.shape
        h = jnp.take(params["tok_emb"], tgt, axis=0) + params["pos_emb"][None, :Tt]
        causal = (jnp.arange(Tt)[:, None] >= jnp.arange(Tt)[None, :])[None, None]
        cross_mask = None
        if src_mask is not None:
            cross_mask = src_mask[:, None, None, :].astype(bool)
        for i in range(config.n_decoder_layers):
            blk = params["decoder"][str(i)]
            x = L.layer_norm_apply(blk["ln1"], h)
            h = h + _attn(blk["self_attn"], x, x, config.n_head, causal)
            x = L.layer_norm_apply(blk["ln_x"], h)
            h = h + _attn(blk["cross_attn"], x, enc, config.n_head, cross_mask)
            h = h + _mlp(blk["mlp"], L.layer_norm_apply(blk["ln2"], h))
        h = L.layer_norm_apply(params["ln_f"], h)
        return h @ params["lm_head"]

    # -- mutations ------------------------------------------------------ #
    @mutation(MutationType.LAYER)
    def add_layer(self, rng: Optional[np.random.Generator] = None) -> Dict:
        rng = derive_rng(rng)
        cfg = self.config
        if bool(rng.integers(0, 2)) and cfg.n_encoder_layers < self.max_layers:
            self._morph(dataclasses.replace(cfg, n_encoder_layers=cfg.n_encoder_layers + 1))
            return {"stack": "encoder"}
        if cfg.n_decoder_layers < self.max_layers:
            self._morph(dataclasses.replace(cfg, n_decoder_layers=cfg.n_decoder_layers + 1))
            return {"stack": "decoder"}
        return self.add_node(rng=rng)

    @mutation(MutationType.LAYER, shrink_params=True)
    def remove_layer(self, rng: Optional[np.random.Generator] = None) -> Dict:
        rng = derive_rng(rng)
        cfg = self.config
        if bool(rng.integers(0, 2)) and cfg.n_encoder_layers > self.min_layers:
            self._morph(dataclasses.replace(cfg, n_encoder_layers=cfg.n_encoder_layers - 1))
            return {"stack": "encoder"}
        if cfg.n_decoder_layers > self.min_layers:
            self._morph(dataclasses.replace(cfg, n_decoder_layers=cfg.n_decoder_layers - 1))
            return {"stack": "decoder"}
        return self.add_node(rng=rng)

    @mutation(MutationType.NODE)
    def add_node(
        self, numb_new_nodes: Optional[int] = None, rng: Optional[np.random.Generator] = None
    ) -> Dict:
        rng = derive_rng(rng)
        cfg = self.config
        if numb_new_nodes is None:
            numb_new_nodes = cfg.n_head * int(rng.choice([4, 8]))
        new_d = min(cfg.d_model + numb_new_nodes, self.max_d_model)
        new_d -= new_d % cfg.n_head
        self._morph(dataclasses.replace(cfg, d_model=new_d, d_ff=None))
        return {"numb_new_nodes": numb_new_nodes}

    @mutation(MutationType.NODE, shrink_params=True)
    def remove_node(
        self, numb_new_nodes: Optional[int] = None, rng: Optional[np.random.Generator] = None
    ) -> Dict:
        rng = derive_rng(rng)
        cfg = self.config
        if numb_new_nodes is None:
            numb_new_nodes = cfg.n_head * int(rng.choice([4, 8]))
        new_d = max(cfg.d_model - numb_new_nodes, self.min_d_model)
        new_d -= new_d % cfg.n_head
        if new_d < self.min_d_model:  # head-divisible floor must not undershoot
            new_d += cfg.n_head
        self._morph(dataclasses.replace(cfg, d_model=new_d, d_ff=None))
        return {"numb_new_nodes": numb_new_nodes}
