"""Custom layer components (parity: agilerl/modules/custom_components.py —
GumbelSoftmax:10, NoisyLinear:38, NewGELU:134, ResidualBlock:152,
SimbaResidualBlock:224).

All are pure functions over dict params (the framework's layer idiom); the
torch-module forms of the reference map to init/apply pairs here.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from agilerl_tpu.algorithms.maddpg import gumbel_softmax as GumbelSoftmax  # noqa: F401
from agilerl_tpu.modules.layers import (  # noqa: F401
    conv2d_apply,
    conv2d_init,
    dense_apply,
    dense_init,
    layer_norm_apply,
    layer_norm_init,
    noisy_dense_apply as NoisyLinear_apply,
    noisy_dense_init as NoisyLinear_init,
)


def NewGELU(x: jax.Array) -> jax.Array:
    """tanh-approx GELU (parity: custom_components.py:134)."""
    return (
        0.5 * x * (1.0 + jnp.tanh(jnp.sqrt(2.0 / jnp.pi) * (x + 0.044715 * x**3)))
    )


def residual_block_init(key: jax.Array, channels: int, kernel: int = 3) -> Dict:
    """Image residual block params (parity: ResidualBlock:152)."""
    k1, k2 = jax.random.split(key)
    return {
        "conv1": conv2d_init(k1, kernel, kernel, channels, channels),
        "norm1": layer_norm_init(channels),
        "conv2": conv2d_init(k2, kernel, kernel, channels, channels),
        "norm2": layer_norm_init(channels),
    }


def residual_block_apply(params: Dict, x: jax.Array) -> jax.Array:
    h = jax.nn.relu(layer_norm_apply(params["norm1"], conv2d_apply(params["conv1"], x, 1, "SAME")))
    h = layer_norm_apply(params["norm2"], conv2d_apply(params["conv2"], h, 1, "SAME"))
    return jax.nn.relu(x + h)


def simba_residual_block_init(key: jax.Array, hidden: int, scale: int = 4) -> Dict:
    """SimBa residual MLP block (parity: SimbaResidualBlock:224)."""
    k1, k2 = jax.random.split(key)
    return {
        "norm": layer_norm_init(hidden),
        "fc1": dense_init(k1, hidden, hidden * scale),
        "fc2": dense_init(k2, hidden * scale, hidden),
    }


def simba_residual_block_apply(params: Dict, x: jax.Array) -> jax.Array:
    h = layer_norm_apply(params["norm"], x)
    h = jax.nn.relu(dense_apply(params["fc1"], h))
    return x + dense_apply(params["fc2"], h)
