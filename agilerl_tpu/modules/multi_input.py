"""Evolvable multi-input encoder for Dict/Tuple observation spaces
(parity: agilerl/modules/multi_input.py — EvolvableMultiInput:65,
build_feature_extractor:353, latent mutations :483,501).

Per-key feature extractors (CNN for image subspaces, MLP for vector subspaces)
are fused by concatenation into a final dense latent layer. Sub-extractors are
themselves evolvable modules, so architecture mutations recurse into a randomly
chosen subnetwork — mirroring the reference's nested-module mutation recursion
(modules/base.py:629) — while latent-dim mutations act on the fusion layer.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from agilerl_tpu.modules import layers as L
from agilerl_tpu.modules.base import EvolvableModule, config_replace, mutation
from agilerl_tpu.modules.cnn import CNNConfig, EvolvableCNN
from agilerl_tpu.modules.mlp import EvolvableMLP, MLPConfig
from agilerl_tpu.typing import MutationType
from agilerl_tpu.utils.rng import derive_rng
from agilerl_tpu.utils.rng import derive_key

# Sub-configs are stored in a tuple of (key, kind, config) so the whole config
# stays hashable/static.
SubCfg = Tuple[str, str, Any]  # (obs key, "cnn"|"mlp", sub config)


@dataclasses.dataclass(frozen=True)
class MultiInputConfig:
    sub_configs: Tuple[SubCfg, ...]
    num_outputs: int
    latent_dim: int = 64
    vector_spaces_mlp: bool = True
    output_activation: Optional[str] = None
    min_latent_dim: int = 16
    max_latent_dim: int = 256


def _build_sub_configs(
    observation_space, feature_dim: int = 64
) -> Tuple[SubCfg, ...]:
    """Auto-derive per-key extractor configs from a Dict/Tuple gym space."""
    from gymnasium import spaces as gspaces

    from agilerl_tpu.utils.spaces import image_shape_nhwc, is_image_space, obs_dim

    if isinstance(observation_space, gspaces.Dict):
        items = list(observation_space.spaces.items())
    else:  # Tuple space
        items = [(str(i), s) for i, s in enumerate(observation_space.spaces)]
    subs = []
    for key, space in items:
        if is_image_space(space):
            h, w, _ = image_shape_nhwc(space)
            # scale the default stack to the image: tiny probe-sized images
            # need kernel<=min(h,w) and stride 1 or the spatial dims collapse
            if min(h, w) >= 8:
                channel, kernel, stride = (16, 16), (3, 3), (2, 2)
            else:
                k = min(2, h, w)
                channel, kernel, stride = (8,), (k,), (1,)
            cfg = CNNConfig(
                input_shape=image_shape_nhwc(space),
                num_outputs=feature_dim,
                channel_size=channel,
                kernel_size=kernel,
                stride_size=stride,
            )
            subs.append((key, "cnn", cfg))
        else:
            cfg = MLPConfig(
                num_inputs=obs_dim(space),
                num_outputs=feature_dim,
                hidden_size=(64,),
                output_vanish=False,
            )
            subs.append((key, "mlp", cfg))
    return tuple(subs)


_SUB_TYPES = {"cnn": EvolvableCNN, "mlp": EvolvableMLP}


class EvolvableMultiInput(EvolvableModule):
    Config = MultiInputConfig

    def __init__(
        self,
        observation_space=None,
        num_outputs: Optional[int] = None,
        key: Optional[jax.Array] = None,
        config: Optional[MultiInputConfig] = None,
        **kwargs,
    ):
        if config is None:
            sub_configs = _build_sub_configs(observation_space)
            config = MultiInputConfig(
                sub_configs=sub_configs, num_outputs=num_outputs, **kwargs
            )
        if key is None:
            key = derive_key()
        super().__init__(config, key)

    @staticmethod
    def init_params(key: jax.Array, config: MultiInputConfig) -> Dict:
        params: Dict = {}
        keys = jax.random.split(key, len(config.sub_configs) + 2)
        total = 0
        for i, (name, kind, sub_cfg) in enumerate(config.sub_configs):
            params[f"sub_{name}"] = _SUB_TYPES[kind].init_params(keys[i], sub_cfg)
            total += sub_cfg.num_outputs
        params["fusion"] = L.dense_init(keys[-2], total, config.latent_dim)
        params["output"] = L.dense_init(keys[-1], config.latent_dim, config.num_outputs)
        return params

    @staticmethod
    def apply(config: MultiInputConfig, params: Dict, x: Any, **_) -> jax.Array:
        feats = []
        for name, kind, sub_cfg in config.sub_configs:
            obs = x[name] if isinstance(x, dict) else x[int(name)]
            feats.append(_SUB_TYPES[kind].apply(sub_cfg, params[f"sub_{name}"], obs))
        h = jnp.concatenate([f.astype(jnp.float32) for f in feats], axis=-1)
        h = jax.nn.relu(L.dense_apply(params["fusion"], h))
        out = L.dense_apply(params["output"], h)
        return L.get_activation(config.output_activation)(out)

    # -- mutations ------------------------------------------------------ #
    @mutation(MutationType.NODE)
    def add_latent_node(
        self, numb_new_nodes: Optional[int] = None, rng: Optional[np.random.Generator] = None
    ) -> Dict:
        """Grow the fusion latent dim (parity: multi_input.py:483)."""
        rng = derive_rng(rng)
        if numb_new_nodes is None:
            numb_new_nodes = int(rng.choice([8, 16, 32]))
        cfg = self.config
        self._morph(
            config_replace(
                cfg, latent_dim=min(cfg.latent_dim + numb_new_nodes, cfg.max_latent_dim)
            )
        )
        return {"numb_new_nodes": numb_new_nodes}

    @mutation(MutationType.NODE, shrink_params=True)
    def remove_latent_node(
        self, numb_new_nodes: Optional[int] = None, rng: Optional[np.random.Generator] = None
    ) -> Dict:
        """Shrink the fusion latent dim (parity: multi_input.py:501)."""
        rng = derive_rng(rng)
        if numb_new_nodes is None:
            numb_new_nodes = int(rng.choice([8, 16, 32]))
        cfg = self.config
        self._morph(
            config_replace(
                cfg, latent_dim=max(cfg.latent_dim - numb_new_nodes, cfg.min_latent_dim)
            )
        )
        return {"numb_new_nodes": numb_new_nodes}

    @mutation(MutationType.LAYER)
    def add_sub_layer(self, rng: Optional[np.random.Generator] = None) -> Dict:
        """Add a layer to a random sub-extractor (nested-module mutation;
        parity: the reference recurses @mutation calls into sub-modules,
        modules/base.py:629)."""
        return self._mutate_sub("add_layer", rng)

    @mutation(MutationType.LAYER, shrink_params=True)
    def remove_sub_layer(self, rng: Optional[np.random.Generator] = None) -> Dict:
        return self._mutate_sub("remove_layer", rng)

    def _mutate_sub(self, method: str, rng) -> Dict:
        rng = derive_rng(rng)
        cfg = self.config
        idx = int(rng.integers(0, len(cfg.sub_configs)))
        name, kind, sub_cfg = cfg.sub_configs[idx]
        # materialise the sub-module, mutate it, write back config + params
        sub_cls = _SUB_TYPES[kind]
        sub = object.__new__(sub_cls)
        sub.config = sub_cfg
        sub._key = self._next_key()
        sub.params = self.params[f"sub_{name}"]
        sub.last_mutation_attr = None
        sub.last_mutation = {}
        getattr(sub, method)(rng=rng)
        new_subs = list(cfg.sub_configs)
        new_subs[idx] = (name, kind, sub.config)
        self.params[f"sub_{name}"] = sub.params
        self.config = config_replace(cfg, sub_configs=tuple(new_subs))
        return {"sub": name, "method": method}
