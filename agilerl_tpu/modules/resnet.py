"""Evolvable ResNet image encoder (parity: agilerl/modules/resnet.py —
EvolvableResNet:12, block/channel mutations :197-241; ResidualBlock in
custom_components.py:152).

NHWC, group-norm-free (layer norm over channels), SAME-padded 3x3 convs so block
count mutations never invalidate spatial dims.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from agilerl_tpu.modules import layers as L
from agilerl_tpu.modules.base import EvolvableModule, config_replace, mutation
from agilerl_tpu.typing import MutationType
from agilerl_tpu.utils.rng import derive_rng
from agilerl_tpu.utils.rng import derive_key


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    input_shape: Tuple[int, ...]  # (H, W, C)
    num_outputs: int
    channel_size: int = 32
    num_blocks: int = 2
    min_blocks: int = 1
    max_blocks: int = 4
    min_channel_size: int = 16
    max_channel_size: int = 128
    output_activation: Optional[str] = None

    def __post_init__(self):
        assert len(self.input_shape) == 3


class EvolvableResNet(EvolvableModule):
    Config = ResNetConfig

    def __init__(
        self,
        input_shape: Optional[Tuple[int, ...]] = None,
        num_outputs: Optional[int] = None,
        key: Optional[jax.Array] = None,
        config: Optional[ResNetConfig] = None,
        **kwargs,
    ):
        if config is None:
            config = ResNetConfig(
                input_shape=tuple(input_shape), num_outputs=num_outputs, **kwargs
            )
        if key is None:
            key = derive_key()
        super().__init__(config, key)

    @staticmethod
    def init_params(key: jax.Array, config: ResNetConfig) -> Dict:
        params: Dict = {}
        c = config.channel_size
        keys = jax.random.split(key, 2 * config.num_blocks + 2)
        params["stem"] = L.conv2d_init(keys[0], 3, 3, config.input_shape[-1], c)
        for i in range(config.num_blocks):
            params[f"block_{i}"] = {
                "conv1": L.conv2d_init(keys[2 * i + 1], 3, 3, c, c),
                "norm1": L.layer_norm_init(c),
                "conv2": L.conv2d_init(keys[2 * i + 2], 3, 3, c, c),
                "norm2": L.layer_norm_init(c),
            }
        params["output"] = L.dense_init(keys[-1], c, config.num_outputs)
        return params

    @staticmethod
    def apply(config: ResNetConfig, params: Dict, x: jax.Array, **_) -> jax.Array:
        h = L.maybe_rescale_image(x)
        squeeze = False
        if h.ndim == 3:
            h = h[None]
            squeeze = True
        h = L.conv2d_apply(params["stem"], h, stride=1, padding="SAME")
        for i in range(config.num_blocks):
            blk = params[f"block_{i}"]
            r = jax.nn.relu(
                L.layer_norm_apply(blk["norm1"], L.conv2d_apply(blk["conv1"], h, 1, "SAME"))
            )
            r = L.layer_norm_apply(blk["norm2"], L.conv2d_apply(blk["conv2"], r, 1, "SAME"))
            h = jax.nn.relu(h + r)
        h = jnp.mean(h, axis=(1, 2))  # global average pool
        out = L.dense_apply(params["output"], h)
        out = L.get_activation(config.output_activation)(out)
        return out[0] if squeeze else out

    # -- mutations ------------------------------------------------------ #
    @mutation(MutationType.LAYER)
    def add_block(self, rng: Optional[np.random.Generator] = None) -> Dict:
        cfg = self.config
        if cfg.num_blocks >= cfg.max_blocks:
            return self.add_channel(rng=rng)
        self._morph(config_replace(cfg, num_blocks=cfg.num_blocks + 1))
        return {}

    @mutation(MutationType.LAYER, shrink_params=True)
    def remove_block(self, rng: Optional[np.random.Generator] = None) -> Dict:
        cfg = self.config
        if cfg.num_blocks <= cfg.min_blocks:
            return self.add_channel(rng=rng)
        self._morph(config_replace(cfg, num_blocks=cfg.num_blocks - 1))
        return {}

    @mutation(MutationType.NODE)
    def add_channel(
        self,
        numb_new_channels: Optional[int] = None,
        rng: Optional[np.random.Generator] = None,
    ) -> Dict:
        rng = derive_rng(rng)
        if numb_new_channels is None:
            numb_new_channels = int(rng.choice([8, 16, 32]))
        cfg = self.config
        self._morph(
            config_replace(
                cfg,
                channel_size=min(cfg.channel_size + numb_new_channels, cfg.max_channel_size),
            )
        )
        return {"numb_new_channels": numb_new_channels}

    @mutation(MutationType.NODE, shrink_params=True)
    def remove_channel(
        self,
        numb_new_channels: Optional[int] = None,
        rng: Optional[np.random.Generator] = None,
    ) -> Dict:
        rng = derive_rng(rng)
        if numb_new_channels is None:
            numb_new_channels = int(rng.choice([8, 16, 32]))
        cfg = self.config
        self._morph(
            config_replace(
                cfg,
                channel_size=max(cfg.channel_size - numb_new_channels, cfg.min_channel_size),
            )
        )
        return {"numb_new_channels": numb_new_channels}
