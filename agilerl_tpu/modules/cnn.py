"""Evolvable CNN (parity: agilerl/modules/cnn.py — EvolvableCNN:224, mutable
kernel sizes MutableKernelSizes:55, mutations add/remove layer/channel + kernel
changes :583-737, shrink_preserve_parameters:418).

TPU-first: NHWC layout (torch reference is NCHW), lax.conv_general_dilated on the
MXU, uint8 obs rescaled on-device. A kernel-size mutation changes the static
config -> XLA recompiles; weights are preserved slab-wise per conv layer.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from agilerl_tpu.modules import layers as L
from agilerl_tpu.modules.base import (
    EvolvableModule,
    config_replace,
    mutation,
    tuple_set,
)
from agilerl_tpu.typing import MutationType
from agilerl_tpu.utils.rng import derive_rng
from agilerl_tpu.utils.rng import derive_key


@dataclasses.dataclass(frozen=True)
class CNNConfig:
    input_shape: Tuple[int, ...]  # (H, W, C) — NHWC
    num_outputs: int
    channel_size: Tuple[int, ...] = (32, 32)
    kernel_size: Tuple[int, ...] = (3, 3)
    stride_size: Tuple[int, ...] = (1, 1)
    activation: str = "ReLU"
    output_activation: Optional[str] = None
    min_hidden_layers: int = 1
    max_hidden_layers: int = 6
    min_channel_size: int = 16
    max_channel_size: int = 256
    layer_norm: bool = True
    init_layers: bool = True

    def __post_init__(self):
        assert len(self.input_shape) == 3, "CNN input must be (H, W, C)"
        assert (
            len(self.channel_size) == len(self.kernel_size) == len(self.stride_size)
        ), "channel/kernel/stride must align"
        # a conv stack that collapses the spatial dims to zero would silently
        # degenerate to a bias-only (input-independent!) network — the dense
        # head on 0 flattened features still "works" (review finding: the
        # multi-input probe's image key was invisible to the agent)
        h, w, _ = self.input_shape
        for k, s in zip(self.kernel_size, self.stride_size):
            h = L.conv_out_size(h, k, s)
            w = L.conv_out_size(w, k, s)
        if h < 1 or w < 1:
            raise ValueError(
                f"CNN arch collapses {self.input_shape[:2]} spatial dims to "
                f"({h}, {w}) — reduce kernel/stride or layer count "
                f"(kernels {self.kernel_size}, strides {self.stride_size})"
            )


def _spatial_dims(config: CNNConfig) -> Tuple[int, int]:
    h, w, _ = config.input_shape
    for k, s in zip(config.kernel_size, config.stride_size):
        h = L.conv_out_size(h, k, s)
        w = L.conv_out_size(w, k, s)
    return h, w


def _valid_arch(config: CNNConfig) -> bool:
    h, w = _spatial_dims(config)
    return h >= 1 and w >= 1


class EvolvableCNN(EvolvableModule):
    Config = CNNConfig

    def __init__(
        self,
        input_shape: Optional[Tuple[int, ...]] = None,
        num_outputs: Optional[int] = None,
        key: Optional[jax.Array] = None,
        config: Optional[CNNConfig] = None,
        **kwargs,
    ):
        if config is None:
            config = CNNConfig(input_shape=tuple(input_shape), num_outputs=num_outputs, **kwargs)
        if key is None:
            key = derive_key()
        super().__init__(config, key)

    # ------------------------------------------------------------------ #
    @staticmethod
    def init_params(key: jax.Array, config: CNNConfig) -> Dict:
        params: Dict = {}
        in_c = config.input_shape[-1]
        chans = (in_c,) + config.channel_size
        keys = jax.random.split(key, len(config.channel_size) + 1)
        for i, (k, _s) in enumerate(zip(config.kernel_size, config.stride_size)):
            params[f"conv_{i}"] = L.conv2d_init(keys[i], k, k, chans[i], chans[i + 1])
            if config.layer_norm:
                params[f"norm_{i}"] = L.layer_norm_init(chans[i + 1])
        h, w = _spatial_dims(config)
        flat = h * w * config.channel_size[-1]
        params["output"] = L.dense_init(keys[-1], flat, config.num_outputs)
        return params

    @staticmethod
    def apply(config: CNNConfig, params: Dict, x: jax.Array, **_) -> jax.Array:
        act = L.get_activation(config.activation)
        out_act = L.get_activation(config.output_activation)
        h = L.maybe_rescale_image(x)
        squeeze = False
        if h.ndim == 3:  # unbatched
            h = h[None]
            squeeze = True
        for i, s in enumerate(config.stride_size):
            h = L.conv2d_apply(params[f"conv_{i}"], h, stride=s)
            if config.layer_norm:
                h = L.layer_norm_apply(params[f"norm_{i}"], h)
            h = act(h)
        h = h.reshape(h.shape[0], -1)
        h = out_act(L.dense_apply(params["output"], h))
        return h[0] if squeeze else h

    # -- mutations ------------------------------------------------------ #
    @mutation(MutationType.LAYER)
    def add_layer(self, rng: Optional[np.random.Generator] = None) -> Dict:
        """Append a conv layer (parity: cnn.py:583)."""
        cfg = self.config
        if len(cfg.channel_size) >= cfg.max_hidden_layers:
            return self.add_channel(rng=rng)
        new = config_replace(
            cfg,
            channel_size=cfg.channel_size + (cfg.channel_size[-1],),
            kernel_size=cfg.kernel_size + (3,),
            stride_size=cfg.stride_size + (1,),
        )
        if not _valid_arch(new):
            return self.add_channel(rng=rng)
        self._morph(new)
        return {}

    @mutation(MutationType.LAYER, shrink_params=True)
    def remove_layer(self, rng: Optional[np.random.Generator] = None) -> Dict:
        """Drop the last conv layer (parity: cnn.py:659)."""
        cfg = self.config
        if len(cfg.channel_size) <= cfg.min_hidden_layers:
            return self.add_channel(rng=rng)
        self._morph(
            config_replace(
                cfg,
                channel_size=cfg.channel_size[:-1],
                kernel_size=cfg.kernel_size[:-1],
                stride_size=cfg.stride_size[:-1],
            )
        )
        return {}

    @mutation(MutationType.NODE)
    def add_channel(
        self,
        hidden_layer: Optional[int] = None,
        numb_new_channels: Optional[int] = None,
        rng: Optional[np.random.Generator] = None,
    ) -> Dict:
        """Grow channels of a random conv layer (parity: cnn.py:707)."""
        rng = derive_rng(rng)
        cfg = self.config
        if hidden_layer is None:
            hidden_layer = int(rng.integers(0, len(cfg.channel_size)))
        hidden_layer = min(hidden_layer, len(cfg.channel_size) - 1)
        if numb_new_channels is None:
            numb_new_channels = int(rng.choice([8, 16, 32]))
        new_c = min(cfg.channel_size[hidden_layer] + numb_new_channels, cfg.max_channel_size)
        self._morph(
            config_replace(cfg, channel_size=tuple_set(cfg.channel_size, hidden_layer, new_c))
        )
        return {"hidden_layer": hidden_layer, "numb_new_channels": numb_new_channels}

    @mutation(MutationType.NODE, shrink_params=True)
    def remove_channel(
        self,
        hidden_layer: Optional[int] = None,
        numb_new_channels: Optional[int] = None,
        rng: Optional[np.random.Generator] = None,
    ) -> Dict:
        """Shrink channels of a random conv layer (parity: cnn.py:737)."""
        rng = derive_rng(rng)
        cfg = self.config
        if hidden_layer is None:
            hidden_layer = int(rng.integers(0, len(cfg.channel_size)))
        hidden_layer = min(hidden_layer, len(cfg.channel_size) - 1)
        if numb_new_channels is None:
            numb_new_channels = int(rng.choice([8, 16, 32]))
        new_c = max(cfg.channel_size[hidden_layer] - numb_new_channels, cfg.min_channel_size)
        self._morph(
            config_replace(cfg, channel_size=tuple_set(cfg.channel_size, hidden_layer, new_c))
        )
        return {"hidden_layer": hidden_layer, "numb_new_channels": numb_new_channels}

    @mutation(MutationType.NODE)
    def change_kernel(
        self,
        kernel_size: Optional[int] = None,
        hidden_layer: Optional[int] = None,
        rng: Optional[np.random.Generator] = None,
    ) -> Dict:
        """Mutate a kernel size (parity: cnn.py:675, MutableKernelSizes:55)."""
        rng = derive_rng(rng)
        cfg = self.config
        if len(cfg.channel_size) > 1:
            if hidden_layer is None:
                hidden_layer = int(rng.integers(1, len(cfg.channel_size)))
        else:
            hidden_layer = 0
        hidden_layer = min(hidden_layer, len(cfg.channel_size) - 1)
        if kernel_size is None:
            kernel_size = int(rng.choice([3, 4, 5, 7]))
        new = config_replace(
            cfg, kernel_size=tuple_set(cfg.kernel_size, hidden_layer, kernel_size)
        )
        if not _valid_arch(new):
            return {"hidden_layer": hidden_layer, "kernel_size": cfg.kernel_size[hidden_layer]}
        self._morph(new)
        return {"hidden_layer": hidden_layer, "kernel_size": kernel_size}
