"""Functional neural-net layer library: pure init/apply pairs over dict params.

This is the substrate for all evolvable modules. Parameters are plain nested
dicts of jax.Array so that weight-preserving architecture morphs (the core of
evolutionary architecture mutation — parity with agilerl/modules/base.py:472
``preserve_parameters``) are straightforward pytree surgery.

Everything here is jit/vmap-friendly: inits take explicit PRNG keys, applies are
pure. Matmul-heavy paths keep operands in float32 params with optional bfloat16
compute (TPU MXU native dtype).
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Params = Dict[str, jax.Array]

# --------------------------------------------------------------------------- #
# Activations (parity: agilerl mlp/cnn activation choices, utils/evolvable_networks)
# --------------------------------------------------------------------------- #

ACTIVATIONS: Dict[str, Callable[[jax.Array], jax.Array]] = {
    "ReLU": jax.nn.relu,
    "Tanh": jnp.tanh,
    "Sigmoid": jax.nn.sigmoid,
    "GELU": jax.nn.gelu,
    "ELU": jax.nn.elu,
    "LeakyReLU": lambda x: jax.nn.leaky_relu(x, 0.01),
    "Softsign": jax.nn.soft_sign,
    "Softplus": jax.nn.softplus,
    "PReLU": lambda x: jax.nn.leaky_relu(x, 0.25),
    "Identity": lambda x: x,
    "Mish": lambda x: x * jnp.tanh(jax.nn.softplus(x)),
    "SiLU": jax.nn.silu,
}


def get_activation(name: Optional[str]) -> Callable[[jax.Array], jax.Array]:
    if name is None:
        return ACTIVATIONS["Identity"]
    if name not in ACTIVATIONS:
        raise ValueError(f"Unknown activation {name!r}; choose from {sorted(ACTIVATIONS)}")
    return ACTIVATIONS[name]


# --------------------------------------------------------------------------- #
# Initializers
# --------------------------------------------------------------------------- #


def kaiming_uniform(key: jax.Array, shape: Tuple[int, ...], fan_in: int) -> jax.Array:
    bound = math.sqrt(1.0 / max(fan_in, 1))
    return jax.random.uniform(key, shape, minval=-bound, maxval=bound, dtype=jnp.float32)


def orthogonal(key: jax.Array, shape: Tuple[int, int], scale: float = 1.0) -> jax.Array:
    return jax.nn.initializers.orthogonal(scale)(key, shape, jnp.float32)


# --------------------------------------------------------------------------- #
# Dense
# --------------------------------------------------------------------------- #


def dense_init(key: jax.Array, in_dim: int, out_dim: int) -> Params:
    wkey, bkey = jax.random.split(key)
    return {
        "kernel": kaiming_uniform(wkey, (in_dim, out_dim), in_dim),
        "bias": kaiming_uniform(bkey, (out_dim,), in_dim),
    }


def dense_apply(params: Params, x: jax.Array) -> jax.Array:
    return x @ params["kernel"] + params["bias"]


# --------------------------------------------------------------------------- #
# Noisy dense (factorised Gaussian noise; parity: NoisyLinear,
# agilerl/modules/custom_components.py:38 — used by Rainbow DQN)
# --------------------------------------------------------------------------- #


def noisy_dense_init(key: jax.Array, in_dim: int, out_dim: int, std_init: float = 0.5) -> Params:
    wkey, bkey = jax.random.split(key)
    mu_range = 1.0 / math.sqrt(in_dim)
    return {
        "kernel_mu": jax.random.uniform(wkey, (in_dim, out_dim), minval=-mu_range, maxval=mu_range),
        "kernel_sigma": jnp.full((in_dim, out_dim), std_init / math.sqrt(in_dim), jnp.float32),
        "bias_mu": jax.random.uniform(bkey, (out_dim,), minval=-mu_range, maxval=mu_range),
        "bias_sigma": jnp.full((out_dim,), std_init / math.sqrt(out_dim), jnp.float32),
    }


def _scaled_noise(key: jax.Array, n: int) -> jax.Array:
    x = jax.random.normal(key, (n,))
    return jnp.sign(x) * jnp.sqrt(jnp.abs(x))


def noisy_dense_apply(
    params: Params, x: jax.Array, key: Optional[jax.Array] = None
) -> jax.Array:
    """Apply a noisy linear layer. key=None -> deterministic (eval) path."""
    if key is None:
        return x @ params["kernel_mu"] + params["bias_mu"]
    in_dim, out_dim = params["kernel_mu"].shape
    kin, kout = jax.random.split(key)
    eps_in = _scaled_noise(kin, in_dim)
    eps_out = _scaled_noise(kout, out_dim)
    kernel = params["kernel_mu"] + params["kernel_sigma"] * jnp.outer(eps_in, eps_out)
    bias = params["bias_mu"] + params["bias_sigma"] * eps_out
    return x @ kernel + bias


# --------------------------------------------------------------------------- #
# LayerNorm
# --------------------------------------------------------------------------- #


def layer_norm_init(dim: int) -> Params:
    return {"scale": jnp.ones((dim,), jnp.float32), "bias": jnp.zeros((dim,), jnp.float32)}


def layer_norm_apply(params: Params, x: jax.Array, eps: float = 1e-5) -> jax.Array:
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    norm = (x - mean) * lax.rsqrt(var + eps)
    return norm * params["scale"] + params["bias"]


def rms_norm_init(dim: int) -> Params:
    return {"scale": jnp.ones((dim,), jnp.float32)}


def rms_norm_apply(params: Params, x: jax.Array, eps: float = 1e-6) -> jax.Array:
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return x * lax.rsqrt(var + eps) * params["scale"]


# --------------------------------------------------------------------------- #
# Conv2D (NHWC — TPU-native layout; the reference uses torch NCHW)
# --------------------------------------------------------------------------- #


def conv2d_init(key: jax.Array, kh: int, kw: int, in_c: int, out_c: int) -> Params:
    wkey, bkey = jax.random.split(key)
    fan_in = kh * kw * in_c
    return {
        "kernel": kaiming_uniform(wkey, (kh, kw, in_c, out_c), fan_in),
        "bias": kaiming_uniform(bkey, (out_c,), fan_in),
    }


def conv2d_apply(
    params: Params, x: jax.Array, stride: int = 1, padding: str = "VALID"
) -> jax.Array:
    y = lax.conv_general_dilated(
        x,
        params["kernel"],
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return y + params["bias"]


def conv_out_size(size: int, kernel: int, stride: int, padding: int = 0) -> int:
    return (size + 2 * padding - kernel) // stride + 1


# --------------------------------------------------------------------------- #
# BatchNorm-free image normalisation helper
# --------------------------------------------------------------------------- #


def maybe_rescale_image(x: jax.Array) -> jax.Array:
    """Rescale uint8 images to [0, 1] floats."""
    if x.dtype == jnp.uint8:
        return x.astype(jnp.float32) / 255.0
    return x.astype(jnp.float32)


# --------------------------------------------------------------------------- #
# LSTM (fused-gate cell; parity: EvolvableLSTM, agilerl/modules/lstm.py:11)
# --------------------------------------------------------------------------- #


def lstm_cell_init(key: jax.Array, in_dim: int, hidden: int) -> Params:
    k1, k2, k3, k4 = jax.random.split(key, 4)
    return {
        "wi": kaiming_uniform(k1, (in_dim, 4 * hidden), in_dim),
        "wh": kaiming_uniform(k2, (hidden, 4 * hidden), hidden),
        "bi": kaiming_uniform(k3, (4 * hidden,), in_dim),
        "bh": kaiming_uniform(k4, (4 * hidden,), hidden),
    }


def lstm_cell_apply(
    params: Params, carry: Tuple[jax.Array, jax.Array], x: jax.Array
) -> Tuple[Tuple[jax.Array, jax.Array], jax.Array]:
    h, c = carry
    gates = x @ params["wi"] + params["bi"] + h @ params["wh"] + params["bh"]
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return (h_new, c_new), h_new


def lstm_scan(
    params: Params, x_seq: jax.Array, h0: jax.Array, c0: jax.Array
) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array]]:
    """Run one LSTM layer over a [T, B, D] sequence with lax.scan."""

    def step(carry, x):
        carry, h = lstm_cell_apply(params, carry, x)
        return carry, h

    (h, c), outs = lax.scan(step, (h0, c0), x_seq)
    return outs, (h, c)


# --------------------------------------------------------------------------- #
# Embedding
# --------------------------------------------------------------------------- #


def embedding_init(key: jax.Array, vocab: int, dim: int, scale: float = 0.02) -> Params:
    return {"embedding": scale * jax.random.normal(key, (vocab, dim), jnp.float32)}


def embedding_apply(params: Params, ids: jax.Array) -> jax.Array:
    return jnp.take(params["embedding"], ids, axis=0)
