"""Evolvable-module core: (static config, params pytree) pairs whose architecture
mutations are pure ``config -> config'`` transitions plus weight-preserving pytree
surgery.

Parity target: agilerl/modules/base.py (EvolvableModule, @mutation decorator,
preserve_parameters:472, mutation-method discovery:629,687, clone:713,
ModuleDict:804). Design difference (TPU-first): the reference mutates live torch
``nn.Module`` objects and re-instantiates networks; here a module *is* an immutable
architecture config plus a dict-of-arrays params tree. Mutating = producing a new
config, initialising fresh params for it, then copying every overlapping slab of
the old weights in. The jitted apply function is derived from the (hashable)
config, so XLA recompilation happens exactly when the architecture changes and
never when only weights/HPs change.
"""

from __future__ import annotations

import copy
import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from agilerl_tpu.typing import MutationMethod, MutationType
from agilerl_tpu.utils.rng import derive_rng

Params = Any


# --------------------------------------------------------------------------- #
# Mutation decorator + discovery
# --------------------------------------------------------------------------- #


def mutation(mutation_type: MutationType, shrink_params: bool = False):
    """Mark a method as an architecture mutation (parity: modules/base.py:27).

    The wrapped method must return a dict of mutation metadata (possibly empty);
    the wrapper records ``last_mutation_attr`` / ``last_mutation`` on the module
    so the HPO engine can mirror the same mutation onto sibling networks
    (e.g. actor -> critics, parity: hpo/mutation.py:829).
    """

    def decorator(fn: Callable) -> Callable:
        def wrapper(self, *args, **kwargs):
            result = fn(self, *args, **kwargs)
            self.last_mutation_attr = fn.__name__
            self.last_mutation = result if isinstance(result, dict) else {}
            return self.last_mutation

        wrapper.__name__ = fn.__name__
        wrapper.__doc__ = fn.__doc__
        wrapper._mutation = MutationMethod(fn, mutation_type, shrink_params)
        return wrapper

    return decorator


class EvolvableModule:
    """Base class for all evolvable neural modules.

    Subclasses define:
      - a frozen dataclass ``Config`` (hashable => usable as a jit static arg)
      - ``init_params(key, config) -> params`` (staticmethod)
      - ``apply(config, params, x, **kw) -> out`` (pure staticmethod)
      - mutation methods decorated with ``@mutation(...)`` that build a new
        config and call ``self._morph(new_config)``.
    """

    def __init__(self, config, key: jax.Array, device: Optional[str] = None):
        self.config = config
        self._key = key
        self.params = self.init_params(self._next_key(), config)
        self.last_mutation_attr: Optional[str] = None
        self.last_mutation: Dict[str, Any] = {}

    # -- RNG plumbing ------------------------------------------------------- #
    def _next_key(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    # -- abstract ----------------------------------------------------------- #
    @staticmethod
    def init_params(key: jax.Array, config) -> Params:  # pragma: no cover
        raise NotImplementedError

    @staticmethod
    def apply(config, params: Params, x, **kwargs):  # pragma: no cover
        raise NotImplementedError

    # -- convenience -------------------------------------------------------- #
    def __call__(self, x, **kwargs):
        return type(self).apply(self.config, self.params, x, **kwargs)

    def forward(self, x, **kwargs):
        return self(x, **kwargs)

    @property
    def init_dict(self) -> Dict[str, Any]:
        """Kwargs able to reconstruct this module (parity: base.py:713)."""
        return {"config": self.config}

    # -- mutation machinery ------------------------------------------------- #
    @classmethod
    def get_mutation_methods(cls) -> Dict[str, MutationMethod]:
        """Discover @mutation-decorated methods (parity: base.py:629)."""
        out: Dict[str, MutationMethod] = {}
        for name in dir(cls):
            attr = getattr(cls, name, None)
            meta = getattr(attr, "_mutation", None)
            if meta is not None:
                out[name] = meta
        return out

    @classmethod
    def layer_mutation_methods(cls) -> List[str]:
        return [
            n for n, m in cls.get_mutation_methods().items()
            if m.mutation_type == MutationType.LAYER
        ]

    @classmethod
    def node_mutation_methods(cls) -> List[str]:
        return [
            n for n, m in cls.get_mutation_methods().items()
            if m.mutation_type == MutationType.NODE
        ]

    def sample_mutation_method(
        self, new_layer_prob: float = 0.2, rng: Optional[np.random.Generator] = None
    ) -> str:
        """Sample a mutation method name, preferring node mutations
        (parity: base.py:687 — layer mutations chosen with prob new_layer_prob)."""
        rng = derive_rng(rng)
        layers = self.layer_mutation_methods()
        nodes = self.node_mutation_methods()
        if layers and (not nodes or rng.random() < new_layer_prob):
            return str(rng.choice(layers))
        if nodes:
            return str(rng.choice(nodes))
        raise ValueError(f"{type(self).__name__} has no mutation methods")

    def apply_mutation(self, name: str, rng: Optional[np.random.Generator] = None) -> Dict:
        method = getattr(self, name)
        try:
            return method(rng=rng)
        except TypeError:
            return method()

    # -- architecture morphing --------------------------------------------- #
    def _morph(self, new_config) -> None:
        """Re-initialise params for ``new_config`` and preserve old weights.

        Parity: recreate_network + preserve_parameters (modules/base.py:472).
        """
        new_params = self.init_params(self._next_key(), new_config)
        self.params = preserve_params(self.params, new_params)
        self.config = new_config

    # -- cloning / state ---------------------------------------------------- #
    def clone(self) -> "EvolvableModule":
        new = object.__new__(type(self))
        new.__dict__.update(
            {k: v for k, v in self.__dict__.items() if k != "params"}
        )
        new.params = jax.tree_util.tree_map(jnp.copy, self.params)
        return new

    def state_dict(self) -> Params:
        return self.params

    def load_state_dict(self, params: Params) -> None:
        self.params = params

    def param_count(self) -> int:
        return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(self.params))


# --------------------------------------------------------------------------- #
# Weight-preserving pytree surgery
# --------------------------------------------------------------------------- #


def preserve_params(old: Params, new: Params) -> Params:
    """Copy every overlapping slab of ``old`` into ``new`` where tree paths match.

    For each leaf present (by path) in both trees, the top-left
    ``min(old.shape, new.shape)`` hyper-rectangle of the old weights is copied
    into the new tensor; any newly-grown region keeps its fresh initialisation.
    This matches the reference's preserve_parameters / shrink_preserve_parameters
    semantics (agilerl/modules/base.py:472, modules/cnn.py:418) as a single pure
    pytree function.
    """
    old_flat = _flatten_with_paths(old)
    new_flat = _flatten_with_paths(new)
    out = dict(new_flat)
    for path, old_leaf in old_flat.items():
        if path not in new_flat:
            continue
        new_leaf = new_flat[path]
        if old_leaf.ndim != new_leaf.ndim:
            continue
        if old_leaf.shape == new_leaf.shape:
            out[path] = old_leaf
            continue
        slices = tuple(
            slice(0, min(o, n)) for o, n in zip(old_leaf.shape, new_leaf.shape)
        )
        out[path] = new_leaf.at[slices].set(old_leaf[slices])
    return _unflatten_from_paths(out, new)


def _flatten_with_paths(tree: Params) -> Dict[Tuple, jax.Array]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = tuple(
            getattr(p, "key", getattr(p, "idx", getattr(p, "name", str(p))))
            for p in path
        )
        flat[key] = leaf
    return flat


def _unflatten_from_paths(flat: Dict[Tuple, jax.Array], template: Params) -> Params:
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in paths_leaves:
        key = tuple(
            getattr(p, "key", getattr(p, "idx", getattr(p, "name", str(p))))
            for p in path
        )
        leaves.append(flat.get(key, leaf))
    return jax.tree_util.tree_unflatten(treedef, leaves)


# --------------------------------------------------------------------------- #
# ModuleDict (per-agent nets for multi-agent algos; parity: base.py:804)
# --------------------------------------------------------------------------- #


class ModuleDict:
    """An ordered dict of EvolvableModules keyed by agent id."""

    def __init__(self, modules: Dict[str, EvolvableModule]):
        self._modules = dict(modules)

    def __getitem__(self, k: str) -> EvolvableModule:
        return self._modules[k]

    def __setitem__(self, k: str, v: EvolvableModule) -> None:
        self._modules[k] = v

    def __iter__(self):
        return iter(self._modules)

    def __len__(self):
        return len(self._modules)

    def keys(self):
        return self._modules.keys()

    def values(self):
        return self._modules.values()

    def items(self):
        return self._modules.items()

    @property
    def params(self) -> Dict[str, Params]:
        return {k: m.params for k, m in self._modules.items()}

    def load_params(self, params: Dict[str, Params]) -> None:
        for k, p in params.items():
            self._modules[k].params = p

    def clone(self) -> "ModuleDict":
        return ModuleDict({k: m.clone() for k, m in self._modules.items()})


def config_replace(config, **changes):
    """dataclasses.replace for frozen config dataclasses."""
    return dataclasses.replace(config, **changes)


def tuple_insert(t: Tuple, idx: int, value) -> Tuple:
    lst = list(t)
    lst.insert(idx, value)
    return tuple(lst)


def tuple_remove(t: Tuple, idx: int) -> Tuple:
    lst = list(t)
    lst.pop(idx)
    return tuple(lst)


def tuple_set(t: Tuple, idx: int, value) -> Tuple:
    lst = list(t)
    lst[idx] = value
    return tuple(lst)
