"""EvolvableGPT (parity: agilerl/modules/gpt.py — EvolvableGPT:16 with
layer/node mutations :592-617, KV-cache generation, estimate_mfu:516;
CausalSelfAttention:679/Block:814 live in llm/model.py as pure functions).

The evolvable wrapper over the Llama-class transformer in llm/model.py: a layer
mutation adds/removes a block (blocks are name-keyed so weight preservation is
pytree surgery); a node mutation grows/shrinks d_model in head-divisible chunks
with slab-wise weight transfer. The reference's from_pretrained GPT-2 import is
replaced by llm/hf.py's HF weight conversion.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from agilerl_tpu.llm import model as M
from agilerl_tpu.modules.base import EvolvableModule, mutation
from agilerl_tpu.typing import MutationType
from agilerl_tpu.utils.profiling import estimate_mfu as _estimate_mfu
from agilerl_tpu.utils.rng import derive_rng
from agilerl_tpu.utils.rng import derive_key


class EvolvableGPT(EvolvableModule):
    Config = M.GPTConfig

    def __init__(
        self,
        vocab_size: Optional[int] = None,
        key: Optional[jax.Array] = None,
        config: Optional[M.GPTConfig] = None,
        min_layers: int = 1,
        max_layers: int = 12,
        min_d_model: int = 64,
        max_d_model: int = 1024,
        min_experts: int = 2,
        max_experts: int = 16,
        **kwargs,
    ):
        if config is None:
            config = M.GPTConfig(vocab_size=vocab_size, **kwargs)
        if key is None:
            key = derive_key()
        self.min_layers = min_layers
        self.max_layers = max_layers
        self.min_d_model = min_d_model
        self.max_d_model = max_d_model
        self.min_experts = min_experts
        self.max_experts = max_experts
        super().__init__(config, key)

    @staticmethod
    def init_params(key: jax.Array, config: M.GPTConfig) -> Dict:
        return M.init_params(key, config)

    @staticmethod
    def apply(config: M.GPTConfig, params: Dict, tokens: jax.Array, **kw):
        if kw.get("return_aux"):
            # MoE models: surface the Switch load-balance loss so training
            # loops can add config.router_aux_weight * aux (review finding:
            # silently dropping it starves the router of balancing gradient)
            logits, caches, aux = M.apply(config, params, tokens, **kw)
            return (logits, aux) if caches is None else (logits, caches, aux)
        logits, caches = M.apply(config, params, tokens, **kw)
        return logits if caches is None else (logits, caches)

    def estimate_mfu(self, tokens_per_step: int, dt: float) -> float:
        """Model FLOPs utilisation (parity: gpt.py:516)."""
        return _estimate_mfu(self.config, tokens_per_step, dt)

    # -- mutations ------------------------------------------------------ #
    @mutation(MutationType.LAYER)
    def add_layer(self, rng: Optional[np.random.Generator] = None) -> Dict:
        cfg = self.config
        if cfg.n_layer >= self.max_layers:
            return self.add_node(rng=rng)
        self._morph(dataclasses.replace(cfg, n_layer=cfg.n_layer + 1))
        return {}

    @mutation(MutationType.LAYER, shrink_params=True)
    def remove_layer(self, rng: Optional[np.random.Generator] = None) -> Dict:
        cfg = self.config
        if cfg.n_layer <= self.min_layers:
            return self.add_node(rng=rng)
        self._morph(dataclasses.replace(cfg, n_layer=cfg.n_layer - 1))
        return {}

    @mutation(MutationType.NODE)
    def add_node(
        self, numb_new_nodes: Optional[int] = None, rng: Optional[np.random.Generator] = None
    ) -> Dict:
        rng = derive_rng(rng)
        cfg = self.config
        if numb_new_nodes is None:
            numb_new_nodes = cfg.n_head * int(rng.choice([4, 8, 16]))
        new_d = min(cfg.d_model + numb_new_nodes, self.max_d_model)
        new_d -= new_d % cfg.n_head  # head_dim stays integral
        self._morph(dataclasses.replace(cfg, d_model=new_d, d_ff=None))
        return {"numb_new_nodes": numb_new_nodes}

    @mutation(MutationType.NODE, shrink_params=True)
    def remove_node(
        self, numb_new_nodes: Optional[int] = None, rng: Optional[np.random.Generator] = None
    ) -> Dict:
        rng = derive_rng(rng)
        cfg = self.config
        if numb_new_nodes is None:
            numb_new_nodes = cfg.n_head * int(rng.choice([4, 8, 16]))
        new_d = max(cfg.d_model - numb_new_nodes, self.min_d_model)
        new_d -= new_d % cfg.n_head
        self._morph(dataclasses.replace(cfg, d_model=new_d, d_ff=None))
        return {"numb_new_nodes": numb_new_nodes}

    # -- expert mutations (MoE models only; beyond reference — evolves the
    # expert count while preserving trained experts via leading-dim slab
    # surgery; dense models fall back to node mutations) ------------------ #
    @mutation(MutationType.NODE)
    def add_expert(self, rng: Optional[np.random.Generator] = None) -> Dict:
        cfg = self.config
        if cfg.n_experts == 0 or cfg.n_experts >= self.max_experts:
            return self.add_node(rng=rng)
        self._morph(dataclasses.replace(cfg, n_experts=cfg.n_experts + 1))
        return {"n_experts": cfg.n_experts + 1}

    @mutation(MutationType.NODE, shrink_params=True)
    def remove_expert(self, rng: Optional[np.random.Generator] = None) -> Dict:
        cfg = self.config
        if cfg.n_experts == 0 or cfg.n_experts <= self.min_experts:
            return self.add_node(rng=rng)
        # top_k must stay <= n_experts
        new_e = cfg.n_experts - 1
        top_k = min(cfg.expert_top_k, new_e)
        self._morph(dataclasses.replace(cfg, n_experts=new_e, expert_top_k=top_k))
        return {"n_experts": new_e}
