"""Evolvable SimBa encoder — residual-block MLP (parity: agilerl/modules/simba.py
EvolvableSimBa:10, SimbaResidualBlock in custom_components.py:224; mutations
add/remove block, add/remove node :147-185).

Block = LayerNorm -> Dense(4h) -> ReLU -> Dense(h) + skip; input projection then
final LayerNorm, matching the SimBa architecture (Lee et al., 2024).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from agilerl_tpu.modules import layers as L
from agilerl_tpu.modules.base import EvolvableModule, config_replace, mutation
from agilerl_tpu.typing import MutationType
from agilerl_tpu.utils.rng import derive_rng
from agilerl_tpu.utils.rng import derive_key


@dataclasses.dataclass(frozen=True)
class SimBaConfig:
    num_inputs: int
    num_outputs: int
    hidden_size: int = 128
    num_blocks: int = 2
    min_blocks: int = 1
    max_blocks: int = 4
    min_nodes: int = 64
    max_nodes: int = 500
    output_activation: Optional[str] = None
    scale_factor: int = 4


class EvolvableSimBa(EvolvableModule):
    Config = SimBaConfig

    def __init__(
        self,
        num_inputs: Optional[int] = None,
        num_outputs: Optional[int] = None,
        key: Optional[jax.Array] = None,
        config: Optional[SimBaConfig] = None,
        **kwargs,
    ):
        if config is None:
            config = SimBaConfig(num_inputs=num_inputs, num_outputs=num_outputs, **kwargs)
        if key is None:
            key = derive_key()
        super().__init__(config, key)

    @staticmethod
    def init_params(key: jax.Array, config: SimBaConfig) -> Dict:
        params: Dict = {}
        keys = jax.random.split(key, 2 * config.num_blocks + 2)
        params["proj"] = L.dense_init(keys[0], config.num_inputs, config.hidden_size)
        wide = config.hidden_size * config.scale_factor
        for i in range(config.num_blocks):
            params[f"block_{i}"] = {
                "norm": L.layer_norm_init(config.hidden_size),
                "fc1": L.dense_init(keys[2 * i + 1], config.hidden_size, wide),
                "fc2": L.dense_init(keys[2 * i + 2], wide, config.hidden_size),
            }
        params["norm_out"] = L.layer_norm_init(config.hidden_size)
        params["output"] = L.dense_init(keys[-1], config.hidden_size, config.num_outputs)
        return params

    @staticmethod
    def apply(config: SimBaConfig, params: Dict, x: jax.Array, **_) -> jax.Array:
        h = L.dense_apply(params["proj"], x.astype(jnp.float32))
        for i in range(config.num_blocks):
            blk = params[f"block_{i}"]
            r = L.layer_norm_apply(blk["norm"], h)
            r = jax.nn.relu(L.dense_apply(blk["fc1"], r))
            r = L.dense_apply(blk["fc2"], r)
            h = h + r
        h = L.layer_norm_apply(params["norm_out"], h)
        out = L.dense_apply(params["output"], h)
        return L.get_activation(config.output_activation)(out)

    # -- mutations ------------------------------------------------------ #
    @mutation(MutationType.LAYER)
    def add_block(self, rng: Optional[np.random.Generator] = None) -> Dict:
        cfg = self.config
        if cfg.num_blocks >= cfg.max_blocks:
            return self.add_node(rng=rng)
        self._morph(config_replace(cfg, num_blocks=cfg.num_blocks + 1))
        return {}

    @mutation(MutationType.LAYER, shrink_params=True)
    def remove_block(self, rng: Optional[np.random.Generator] = None) -> Dict:
        cfg = self.config
        if cfg.num_blocks <= cfg.min_blocks:
            return self.add_node(rng=rng)
        self._morph(config_replace(cfg, num_blocks=cfg.num_blocks - 1))
        return {}

    @mutation(MutationType.NODE)
    def add_node(
        self, numb_new_nodes: Optional[int] = None, rng: Optional[np.random.Generator] = None
    ) -> Dict:
        rng = derive_rng(rng)
        if numb_new_nodes is None:
            numb_new_nodes = int(rng.choice([16, 32, 64]))
        cfg = self.config
        self._morph(
            config_replace(cfg, hidden_size=min(cfg.hidden_size + numb_new_nodes, cfg.max_nodes))
        )
        return {"numb_new_nodes": numb_new_nodes}

    @mutation(MutationType.NODE, shrink_params=True)
    def remove_node(
        self, numb_new_nodes: Optional[int] = None, rng: Optional[np.random.Generator] = None
    ) -> Dict:
        rng = derive_rng(rng)
        if numb_new_nodes is None:
            numb_new_nodes = int(rng.choice([16, 32, 64]))
        cfg = self.config
        self._morph(
            config_replace(cfg, hidden_size=max(cfg.hidden_size - numb_new_nodes, cfg.min_nodes))
        )
        return {"numb_new_nodes": numb_new_nodes}
