"""Recurrent PPO on a MiniGrid-style partially-observable gridworld
(parity: demos/demo_on_policy_rnn_minigrid.py).

The reference drives `MiniGrid-Unlock` through gym wrappers; this demo uses a
JAX-native MiniGrid-Empty-class env — same structure (egocentric 3x3 view,
turn-left/turn-right/forward actions, minigrid's ``1 - 0.9*t/T`` success
reward), but a pure-JAX state machine so the whole rollout stays on device
(agilerl_tpu/envs/core.py design). The agent never observes its own position:
it must integrate its view history to navigate, which is what the LSTM
encoder provides. If the `minigrid` package is installed, the same agent
config also runs on the real thing via `make_vect_envs` + an obs wrapper."""

# allow running directly as `python <dir>/<script>.py` from a source checkout
import os as _os, sys as _sys  # noqa: E402
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
if _os.environ.get("JAX_PLATFORMS"):  # some plugin backends ignore the env var
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

from typing import NamedTuple

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from agilerl_tpu.algorithms import PPO
from agilerl_tpu.envs import JaxVecEnv
from agilerl_tpu.envs.core import JaxEnv
from agilerl_tpu.rollouts.on_policy import collect_rollouts

SIZE = 7          # grid incl. walls; interior is 5x5
MAX_STEPS = 64
# agent directions: 0=E, 1=S, 2=W, 3=N
DIR_VEC = jnp.array([[1, 0], [0, 1], [-1, 0], [0, -1]], jnp.int32)
CORNERS = jnp.array([[1, 1], [1, 5], [5, 1], [5, 5]], jnp.int32)


class GridState(NamedTuple):
    pos: jax.Array    # [2] int32
    dir: jax.Array    # [] int32
    goal: jax.Array   # [2] int32
    t: jax.Array      # [] int32


class MiniGridEmpty(JaxEnv):
    """Egocentric 3x3 view (wall + goal channels) + direction one-hot."""

    observation_space = gym.spaces.Box(low=0.0, high=1.0, shape=(22,))
    action_space = gym.spaces.Discrete(3)  # 0=turn left, 1=turn right, 2=forward
    max_episode_steps = MAX_STEPS

    def _obs(self, state: GridState) -> jax.Array:
        dx = jnp.arange(-1, 2)
        xs = state.pos[0] + dx[None, :]          # [3, 3] grid of x coords
        ys = state.pos[1] + dx[:, None]
        wall = ((xs <= 0) | (xs >= SIZE - 1) | (ys <= 0) | (ys >= SIZE - 1))
        goal = (xs == state.goal[0]) & (ys == state.goal[1])
        view = jnp.stack([wall, goal], axis=-1).astype(jnp.float32)  # [3,3,2]
        return jnp.concatenate(
            [view.reshape(-1), jax.nn.one_hot(state.dir, 4)]
        )

    def reset_fn(self, key):
        k_goal, k_dir = jax.random.split(key)
        goal = CORNERS[jax.random.randint(k_goal, (), 0, 4)]
        state = GridState(
            pos=jnp.array([SIZE // 2, SIZE // 2], jnp.int32),
            dir=jax.random.randint(k_dir, (), 0, 4).astype(jnp.int32),
            goal=goal, t=jnp.zeros((), jnp.int32),
        )
        return state, self._obs(state)

    def step_fn(self, state, action, key):
        turn = jnp.where(action == 0, -1, jnp.where(action == 1, 1, 0))
        new_dir = (state.dir + turn) % 4
        step_vec = DIR_VEC[new_dir] * (action == 2)
        new_pos = jnp.clip(state.pos + step_vec, 1, SIZE - 2)
        t = state.t + 1
        state = GridState(new_pos, new_dir, state.goal, t)
        reached = jnp.all(new_pos == state.goal)
        reward = jnp.where(reached, 1.0 - 0.9 * t / MAX_STEPS, 0.0)
        return (state, self._obs(state), reward.astype(jnp.float32),
                reached, jnp.zeros((), bool))


if __name__ == "__main__":
    num_envs = 16
    env = JaxVecEnv(MiniGridEmpty(), num_envs=num_envs, seed=0)
    agent = PPO(
        env.single_observation_space, env.single_action_space,
        num_envs=num_envs, learn_step=256, batch_size=256, update_epochs=4,
        lr=2e-3, gamma=0.98, gae_lambda=0.95, ent_coef=0.02,
        recurrent=True, seed=0,
        net_config={"latent_dim": 64, "recurrent": True,
                    "encoder_config": {"hidden_size": 64}},
    )
    print("===== Recurrent PPO on MiniGrid-Empty (JAX-native) =====")
    for it in range(40):
        collect_rollouts(agent, env, n_steps=agent.learn_step)
        agent.learn()
        if it % 5 == 0:
            fitness = agent.test(env, max_steps=MAX_STEPS, loop=1)
            print(f"iter {it:3d}  mean episode return {fitness:6.3f} "
                  f"(reach-goal > 0.4)")
    print("final:", agent.test(env, max_steps=MAX_STEPS, loop=3))
