"""Custom networks demo (parity: demos/demo_custom_network.py).

Two ways to bring your own architecture:

1. **Native**: define a custom evolvable encoder — a frozen config dataclass +
   ``init_params``/``apply`` + ``@mutation`` methods — register it in
   ``ENCODER_TYPES``, and every algorithm, tournament, and mutation in the
   framework can drive it (the metaclass discovers the mutation methods; no
   other wiring). This replaces subclassing ``nn.Module``: modules here are
   (config, params-pytree) pairs so they stay jit/vmap-compatible.

2. **Torch import**: ``MakeEvolvable(network, input_tensor)`` introspects an
   existing ``torch.nn`` model (as the reference's deprecated wrapper does),
   rebuilds it as an evolvable JAX module, and imports the trained weights.
"""

# allow running directly as `python <dir>/<script>.py` from a source checkout
import os as _os, sys as _sys  # noqa: E402
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
if _os.environ.get("JAX_PLATFORMS"):  # some plugin backends ignore the env var
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from agilerl_tpu.components import ReplayBuffer
from agilerl_tpu.hpo import Mutations, TournamentSelection
from agilerl_tpu.modules import layers as L
from agilerl_tpu.modules.base import EvolvableModule, config_replace, mutation
from agilerl_tpu.modules.mlp import MLPConfig
from agilerl_tpu.networks.base import ENCODER_TYPES, NetworkConfig
from agilerl_tpu.training.train_off_policy import train_off_policy
from agilerl_tpu.typing import MutationType
from agilerl_tpu.utils.utils import create_population, make_vect_envs


# ----------------------------------------------------------------------- #
# 1. a custom evolvable encoder: gated residual MLP
# ----------------------------------------------------------------------- #


@dataclasses.dataclass(frozen=True)
class GatedMLPConfig:
    num_inputs: int
    num_outputs: int
    hidden_size: int = 64
    num_blocks: int = 1
    min_blocks: int = 1
    max_blocks: int = 3


class EvolvableGatedMLP(EvolvableModule):
    """x -> proj -> [h + sigmoid(gate(h)) * fc(h)] x blocks -> out."""

    Config = GatedMLPConfig

    def __init__(self, key=None, config: Optional[GatedMLPConfig] = None, **kw):
        if config is None:
            config = GatedMLPConfig(**kw)
        if key is None:
            key = jax.random.PRNGKey(np.random.randint(0, 2**31 - 1))
        super().__init__(config, key)

    @staticmethod
    def init_params(key: jax.Array, config: GatedMLPConfig) -> Dict:
        ks = jax.random.split(key, 2 * config.num_blocks + 2)
        params = {"proj": L.dense_init(ks[0], config.num_inputs, config.hidden_size)}
        for i in range(config.num_blocks):
            params[f"block_{i}"] = {
                "gate": L.dense_init(ks[2 * i + 1], config.hidden_size, config.hidden_size),
                "fc": L.dense_init(ks[2 * i + 2], config.hidden_size, config.hidden_size),
            }
        params["out"] = L.dense_init(ks[-1], config.hidden_size, config.num_outputs)
        return params

    @staticmethod
    def apply(config: GatedMLPConfig, params: Dict, x: jax.Array, **_) -> jax.Array:
        h = jax.nn.relu(L.dense_apply(params["proj"], x.astype(jnp.float32)))
        for i in range(config.num_blocks):
            blk = params[f"block_{i}"]
            gate = jax.nn.sigmoid(L.dense_apply(blk["gate"], h))
            h = h + gate * jax.nn.relu(L.dense_apply(blk["fc"], h))
        return L.dense_apply(params["out"], h)

    @mutation(MutationType.LAYER)
    def add_block(self, rng=None) -> Dict:
        cfg = self.config
        if cfg.num_blocks >= cfg.max_blocks:
            return {}
        self._morph(config_replace(cfg, num_blocks=cfg.num_blocks + 1))
        return {}

    @mutation(MutationType.LAYER, shrink_params=True)
    def remove_block(self, rng=None) -> Dict:
        cfg = self.config
        if cfg.num_blocks <= cfg.min_blocks:
            return {}
        self._morph(config_replace(cfg, num_blocks=cfg.num_blocks - 1))
        return {}


ENCODER_TYPES["gated_mlp"] = EvolvableGatedMLP  # <- the whole registration


def demo_native_custom_encoder():
    print("--- custom evolvable encoder inside the full RLOps loop ---")
    env = make_vect_envs("CartPole-v1", num_envs=8)
    latent = 32
    cfg = NetworkConfig(
        encoder_kind="gated_mlp",
        encoder=GatedMLPConfig(num_inputs=4, num_outputs=latent),
        head=MLPConfig(num_inputs=latent, num_outputs=2, hidden_size=(64,)),
        latent_dim=latent,
    )
    pop = create_population(
        "DQN", env.single_observation_space, env.single_action_space,
        population_size=2, net_config={"config": cfg},
        INIT_HP={"BATCH_SIZE": 64, "LR": 1e-3, "LEARN_STEP": 4, "DOUBLE": True},
        seed=7,
    )
    memory = ReplayBuffer(max_size=10_000)
    tournament = TournamentSelection(2, True, 2, 1)
    mutations = Mutations(no_mutation=0.3, architecture=0.5, parameters=0.2,
                          activation=0.0, rl_hp=0.0)
    pop, fitnesses = train_off_policy(
        env, "CartPole-v1", "DQN", pop, memory,
        max_steps=6_000, evo_steps=2_000, eval_loop=1,
        eps_start=1.0, eps_end=0.1, eps_decay=0.995,
        tournament=tournament, mutation=mutations, verbose=False,
    )
    for agent in pop:
        enc_cfg = agent.actor.config.encoder
        print(f"  agent {agent.index}: blocks={enc_cfg.num_blocks} "
              f"hidden={enc_cfg.hidden_size} fitness={agent.fitness[-1]:.1f}")
    env.close()


# ----------------------------------------------------------------------- #
# 2. import an existing torch model
# ----------------------------------------------------------------------- #


def demo_torch_import():
    try:
        import torch
        from torch import nn
    except ImportError:
        print("--- torch not installed; skipping torch-import demo ---")
        return
    from agilerl_tpu.wrappers.make_evolvable import MakeEvolvable

    print("--- MakeEvolvable: import a trained torch net ---")
    torch_net = nn.Sequential(
        nn.Linear(4, 32), nn.ReLU(), nn.Linear(32, 32), nn.ReLU(), nn.Linear(32, 2)
    )
    x = torch.randn(5, 4)
    evolvable = MakeEvolvable(torch_net, input_tensor=x, key=jax.random.PRNGKey(0))
    got = np.asarray(evolvable(x.numpy()))
    want = torch_net(x).detach().numpy()
    print(f"  imported weights match torch forward: "
          f"max abs err {np.abs(got - want).max():.2e}")
    print(f"  mutation methods discovered: "
          f"{sorted(evolvable.get_mutation_methods())}")


if __name__ == "__main__":
    print("===== agilerl_tpu custom network demo =====")
    demo_native_custom_encoder()
    demo_torch_import()
