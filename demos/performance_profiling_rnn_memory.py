"""Profiling demo — recurrent PPO on the on-device memory task (parity:
demos/performance_flamegraph_rnn_memory.py).

Same workload as demo_on_policy_rnn_memory.py but instrumented: JAX-native env
(no host boundary) + LSTM PPO, traced with jax.profiler. Compare against
performance_profiling_lander_rnn.py to see how much the host env costs."""

# allow running directly as `python <dir>/<script>.py` from a source checkout
import os as _os, sys as _sys  # noqa: E402
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
if _os.environ.get("JAX_PLATFORMS"):  # some plugin backends ignore the env var
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import time

from agilerl_tpu.algorithms import PPO
from agilerl_tpu.envs import JaxVecEnv
from agilerl_tpu.envs.probe import MemoryEnv
from agilerl_tpu.rollouts.on_policy import collect_rollouts
from agilerl_tpu.utils.profiling import StepTimer, profile_trace

if __name__ == "__main__":
    num_envs = 16
    env = JaxVecEnv(MemoryEnv(), num_envs=num_envs, seed=0)
    agent = PPO(
        env.single_observation_space, env.single_action_space,
        num_envs=num_envs, learn_step=128, batch_size=128, update_epochs=2,
        lr=3e-3, recurrent=True, seed=0,
        net_config={"latent_dim": 32, "recurrent": True,
                    "encoder_config": {"hidden_size": 32}},
    )
    collect_rollouts(agent, env, n_steps=agent.learn_step)  # warm up
    agent.learn()

    timer = StepTimer()
    timer.tick()
    t0 = time.perf_counter()
    with profile_trace("/tmp/agilerl_tpu_trace_rnn_memory"):
        for _ in range(5):
            collect_rollouts(agent, env, n_steps=agent.learn_step)
            agent.learn()
            timer.tick()
    dt = time.perf_counter() - t0
    print("trace written to /tmp/agilerl_tpu_trace_rnn_memory")
    print(f"mean iteration {timer.mean_step_time * 1e3:.1f} ms; "
          f"{5 * agent.learn_step * num_envs / dt:,.0f} env-steps/sec "
          f"(rollout+BPTT learn)")
