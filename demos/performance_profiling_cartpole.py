"""Profiling demo (parity: demos/performance_flamegraph_cartpole.py — cProfile/
torch.profiler flamegraphs become jax.profiler traces + on-device step timing).

Writes an XLA trace viewable in TensorBoard/Perfetto and prints StepTimer
percentiles for the jitted EvoPPO generation step.
"""

# allow running directly as `python <dir>/<script>.py` from a source checkout
import os as _os, sys as _sys  # noqa: E402
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
if _os.environ.get("JAX_PLATFORMS"):  # some plugin backends ignore the env var
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import jax
import optax

from agilerl_tpu.envs import CartPole
from agilerl_tpu.modules.mlp import MLPConfig
from agilerl_tpu.networks import distributions as D
from agilerl_tpu.networks.base import NetworkConfig, default_encoder_config
from agilerl_tpu.parallel.population import EvoPPO
from agilerl_tpu.utils.profiling import StepTimer, profile_trace

if __name__ == "__main__":
    env = CartPole()
    kind, enc = default_encoder_config(
        env.observation_space, latent_dim=64, encoder_config={"hidden_size": (64,)}
    )
    actor_cfg = NetworkConfig(
        encoder_kind=kind, encoder=enc,
        head=MLPConfig(num_inputs=64, num_outputs=2, hidden_size=(64,)),
        latent_dim=64,
    )
    critic_cfg = NetworkConfig(
        encoder_kind=kind, encoder=enc,
        head=MLPConfig(num_inputs=64, num_outputs=1, hidden_size=(64,)),
        latent_dim=64,
    )
    evo = EvoPPO(env, actor_cfg, critic_cfg,
                 D.dist_config_from_space(env.action_space), optax.adam(3e-4),
                 num_envs=32, rollout_len=32, update_epochs=1, num_minibatches=4)
    pop = evo.init_population(jax.random.PRNGKey(0), 8)
    gen = evo.make_vmap_generation()
    pop, fit = gen(pop, jax.random.PRNGKey(1))  # compile
    jax.block_until_ready(fit)

    timer = StepTimer()
    timer.tick()
    with profile_trace("/tmp/agilerl_tpu_trace"):
        for i in range(5):
            pop, fit = gen(pop, jax.random.PRNGKey(2 + i))
            jax.block_until_ready(fit)
            timer.tick()
    steps_per_gen = 8 * 32 * 32  # pop x envs x rollout
    print("trace written to /tmp/agilerl_tpu_trace (open in TensorBoard)")
    print(f"mean generation time {timer.mean_step_time * 1e3:.1f} ms "
          f"({timer.throughput(steps_per_gen):,.0f} env-steps/sec)")
