"""Profiling demo — recurrent PPO rollout+BPTT learn (parity:
demos/performance_flamegraph_lunar_lander_rnn.py).

Profiles the two phases of recurrent on-policy training separately: hidden-
state-carrying rollout collection and the BPTT sequence learn. The trace shows
the scan-structured LSTM forward; the printed split shows where a recurrent
workload actually spends its time."""

# allow running directly as `python <dir>/<script>.py` from a source checkout
import os as _os, sys as _sys  # noqa: E402
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
if _os.environ.get("JAX_PLATFORMS"):  # some plugin backends ignore the env var
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import time

from agilerl_tpu.algorithms import PPO
from agilerl_tpu.rollouts.on_policy import collect_rollouts
from agilerl_tpu.utils.profiling import profile_trace
from agilerl_tpu.utils.utils import make_vect_envs

if __name__ == "__main__":
    num_envs = 8
    env = make_vect_envs("LunarLander-v3", num_envs=num_envs)
    agent = PPO(
        env.single_observation_space, env.single_action_space,
        num_envs=num_envs, learn_step=256, batch_size=256, update_epochs=2,
        lr=3e-4, recurrent=True, seed=0,
        net_config={"latent_dim": 64, "recurrent": True,
                    "encoder_config": {"hidden_size": 64}},
    )
    # warm up the jit caches outside the trace
    collect_rollouts(agent, env, n_steps=agent.learn_step)
    agent.learn()

    t_roll = t_learn = 0.0
    with profile_trace("/tmp/agilerl_tpu_trace_lander_rnn"):
        for _ in range(3):
            t0 = time.perf_counter()
            collect_rollouts(agent, env, n_steps=agent.learn_step)
            t1 = time.perf_counter()
            agent.learn()
            t2 = time.perf_counter()
            t_roll += t1 - t0
            t_learn += t2 - t1
    env.close()
    total = t_roll + t_learn
    print("trace written to /tmp/agilerl_tpu_trace_lander_rnn")
    print(f"recurrent rollout {t_roll:6.2f}s ({100 * t_roll / total:4.1f}%) | "
          f"BPTT learn {t_learn:6.2f}s ({100 * t_learn / total:4.1f}%)")
