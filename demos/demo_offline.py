"""Offline RL demo: CQN on a random-policy CartPole dataset
(parity: demos/demo_offline.py — the bundled h5 dataset is replaced by
on-demand collection, utils/minari_utils.collect_offline_dataset)."""

# allow running directly as `python <dir>/<script>.py` from a source checkout
import os as _os, sys as _sys  # noqa: E402
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
if _os.environ.get("JAX_PLATFORMS"):  # some plugin backends ignore the env var
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

from agilerl_tpu.components import ReplayBuffer
from agilerl_tpu.hpo import Mutations, TournamentSelection
from agilerl_tpu.training.train_offline import train_offline
from agilerl_tpu.utils.minari_utils import collect_offline_dataset
from agilerl_tpu.utils.utils import create_population, make_vect_envs

if __name__ == "__main__":
    env = make_vect_envs("CartPole-v1", num_envs=8)
    dataset = collect_offline_dataset(env, steps=20_000, epsilon=1.0)
    pop = create_population(
        "CQN", env.single_observation_space, env.single_action_space,
        population_size=4,
        net_config={"latent_dim": 32, "encoder_config": {"hidden_size": (64,)}},
        INIT_HP={"BATCH_SIZE": 128, "LR": 1e-3, "LEARN_STEP": 1},
        seed=42,
    )
    memory = ReplayBuffer(max_size=len(dataset["rewards"]))
    tournament = TournamentSelection(2, True, 4, 1)
    mutations = Mutations(no_mutation=0.4, architecture=0.2, parameters=0.2,
                          activation=0.0, rl_hp=0.2)
    pop, fitnesses = train_offline(
        env, "CartPole-v1", dataset, "CQN", pop, memory,
        max_steps=30_000, evo_steps=3_000,
        tournament=tournament, mutation=mutations,
    )
    print("best fitness:", max(max(f) for f in fitnesses))
