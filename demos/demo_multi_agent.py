"""Evolutionary MADDPG on the JAX SimpleSpread env (parity:
demos/demo_multi_agent.py over PettingZoo simple_speaker_listener)."""

# allow running directly as `python <dir>/<script>.py` from a source checkout
import os as _os, sys as _sys  # noqa: E402
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
if _os.environ.get("JAX_PLATFORMS"):  # some plugin backends ignore the env var
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import numpy as np

from agilerl_tpu.components import MultiAgentReplayBuffer
from agilerl_tpu.envs.multi_agent import MultiAgentJaxVecEnv, SimpleSpreadJax
from agilerl_tpu.hpo import Mutations, TournamentSelection
from agilerl_tpu.training.train_multi_agent_off_policy import (
    train_multi_agent_off_policy,
)
from agilerl_tpu.utils.utils import create_population

if __name__ == "__main__":
    env = MultiAgentJaxVecEnv(SimpleSpreadJax(n_agents=2), num_envs=8, seed=0)
    NET_CONFIG = {"latent_dim": 32, "encoder_config": {"hidden_size": (64,)}}
    pop = create_population(
        "MADDPG", env.observation_spaces, env.action_spaces,
        net_config=NET_CONFIG, population_size=4, seed=0,
        agent_ids=env.agent_ids,
    )
    memory = MultiAgentReplayBuffer(max_size=100_000, agent_ids=env.agent_ids)
    tournament = TournamentSelection(2, True, 4, eval_loop=1)
    mutations = Mutations(no_mutation=0.4, architecture=0.2, parameters=0.2,
                          activation=0.0, rl_hp=0.2)
    pop, fitnesses = train_multi_agent_off_policy(
        env, "SimpleSpread", "MADDPG", pop, memory,
        max_steps=100_000, evo_steps=10_000,
        tournament=tournament, mutation=mutations, verbose=True,
    )
