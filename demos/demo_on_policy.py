"""Evolutionary PPO on CartPole (parity: demos/demo_on_policy.py)."""

# allow running directly as `python <dir>/<script>.py` from a source checkout
import os as _os, sys as _sys  # noqa: E402
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
if _os.environ.get("JAX_PLATFORMS"):  # some plugin backends ignore the env var
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

from agilerl_tpu.hpo import Mutations, TournamentSelection
from agilerl_tpu.training.train_on_policy import train_on_policy
from agilerl_tpu.utils.utils import create_population, make_vect_envs

if __name__ == "__main__":
    NET_CONFIG = {"latent_dim": 32, "encoder_config": {"hidden_size": (64,)}}
    NUM_ENVS = 16

    env = make_vect_envs("CartPole-v1", num_envs=NUM_ENVS)
    pop = create_population(
        "PPO", env.single_observation_space, env.single_action_space,
        net_config=NET_CONFIG, population_size=4, num_envs=NUM_ENVS,
        learn_step=128, batch_size=256, lr=3e-4, seed=42,
    )
    tournament = TournamentSelection(2, True, 4, eval_loop=1)
    mutations = Mutations(no_mutation=0.4, architecture=0.2, parameters=0.2,
                          activation=0.0, rl_hp=0.2)
    pop, fitnesses = train_on_policy(
        env, "CartPole-v1", "PPO", pop,
        max_steps=100_000, evo_steps=10_240,
        tournament=tournament, mutation=mutations, verbose=True,
    )
    print("best fitness:", max(max(f) for f in fitnesses))
