"""Contextual-bandit demo: NeuralUCB on the iris labelled-data bandit
(parity: demos/demo_bandit.py — BanditEnv wraps a classification dataset;
reward 1 for the correct arm)."""

# allow running directly as `python <dir>/<script>.py` from a source checkout
import os as _os, sys as _sys  # noqa: E402
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import numpy as np

from agilerl_tpu.components import ReplayBuffer
from agilerl_tpu.hpo import Mutations, TournamentSelection
from agilerl_tpu.training.train_bandits import train_bandits
from agilerl_tpu.utils.utils import create_population
from agilerl_tpu.wrappers import BanditEnv

if __name__ == "__main__":
    # synthetic 3-class separable dataset (sklearn-free iris stand-in)
    rng = np.random.default_rng(0)
    n, d, k = 300, 4, 3
    centers = rng.normal(size=(k, d)) * 2.0
    labels = rng.integers(0, k, n)
    features = centers[labels] + rng.normal(size=(n, d)) * 0.5
    env = BanditEnv(features, labels)

    pop = create_population(
        "NeuralUCB", env.observation_space, env.action_space,
        population_size=4,
        net_config={"latent_dim": 32, "encoder_config": {"hidden_size": (64,)}},
        INIT_HP={"BATCH_SIZE": 64, "LR": 1e-3, "LAMBDA": 1.0, "REG": 0.000625,
                 "LEARN_STEP": 2},
        seed=42,
    )
    memory = ReplayBuffer(max_size=10_000)
    tournament = TournamentSelection(2, True, 4, 1)
    mutations = Mutations(no_mutation=0.4, architecture=0.2, parameters=0.2,
                          activation=0.0, rl_hp=0.2)
    pop, fitnesses = train_bandits(
        env, "iris-bandit", "NeuralUCB", pop, memory,
        max_steps=8_000, evo_steps=1_000,
        tournament=tournament, mutation=mutations,
    )
    print("best regret-free fitness:", max(max(f) for f in fitnesses))
