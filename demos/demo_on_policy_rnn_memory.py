"""Recurrent PPO on a memory task that REQUIRES memory
(parity: demos/demo_on_policy_rnn_memory.py — the cue is shown only at t=0;
a flat PPO cannot beat chance, the LSTM-encoder PPO can)."""

# allow running directly as `python <dir>/<script>.py` from a source checkout
import os as _os, sys as _sys  # noqa: E402
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
if _os.environ.get("JAX_PLATFORMS"):  # some plugin backends ignore the env var
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

from agilerl_tpu.algorithms import PPO
from agilerl_tpu.envs import JaxVecEnv
from agilerl_tpu.envs.probe import MemoryEnv
from agilerl_tpu.rollouts.on_policy import collect_rollouts

if __name__ == "__main__":
    env = JaxVecEnv(MemoryEnv(), num_envs=16, seed=0)
    agent = PPO(
        env.single_observation_space, env.single_action_space,
        num_envs=16, learn_step=64, batch_size=128, update_epochs=4,
        lr=3e-3, gamma=0.9, ent_coef=0.01, seed=0, recurrent=True,
        net_config={"latent_dim": 32, "recurrent": True,
                    "encoder_config": {"hidden_size": 32}},
    )
    for it in range(80):
        collect_rollouts(agent, env, n_steps=agent.learn_step)
        agent.learn()
        if it % 10 == 0:
            fitness = agent.test(env, max_steps=64, loop=1)
            print(f"iter {it:3d} fitness {fitness:+.3f}  (chance 0.0, max +1.0)")
    final = agent.test(env, max_steps=64, loop=3)
    print("final fitness:", final)
