"""Distributed evolutionary DQN on CartPole — the TPU-native equivalent of the
reference's `accelerate launch` DDP demo (parity: demos/demo_off_policy_distributed.py).

Where the reference wraps torch models in HF Accelerate and splits replay
batches across ranks, here the WHOLE evolutionary generation (rollout -> TD
updates -> fitness -> tournament -> mutation) is ONE SPMD program: the
population is sharded over a `pop` mesh axis with `shard_map`, each device
trains its shard, and evolution all-gathers fitness over ICI
(agilerl_tpu/parallel/off_policy.py make_pod_generation). There is no launcher,
no process group, no gradient hooks — one `python` invocation, any mesh size.

Run on a host with one device via a virtual 8-device CPU mesh:
    JAX_PLATFORMS=cpu python demos/demo_off_policy_distributed.py
"""

# allow running directly as `python <dir>/<script>.py` from a source checkout
import os as _os, sys as _sys  # noqa: E402
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
if _os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    # single-host demo: fabricate an 8-device CPU mesh (SURVEY.md §4 — JAX
    # tests collectives for real where the reference fakes world-size 1)
    _flags = _os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        _os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import jax
import numpy as np
import optax
from jax.sharding import Mesh

from agilerl_tpu.envs import CartPole
from agilerl_tpu.modules.mlp import MLPConfig
from agilerl_tpu.networks.base import NetworkConfig, default_encoder_config
from agilerl_tpu.parallel.off_policy import EvoDQN

if __name__ == "__main__":
    devices = jax.devices()
    mesh = Mesh(np.asarray(devices), axis_names=("pop",))
    members_per_device = 2
    pop_size = members_per_device * len(devices)
    print(f"===== agilerl_tpu distributed off-policy demo =====\n"
          f"devices: {len(devices)} ({devices[0].platform}), "
          f"population {pop_size} ({members_per_device}/device)")

    env = CartPole()
    kind, enc = default_encoder_config(env.observation_space, latent_dim=32,
                                       encoder_config={"hidden_size": (64,)})
    net = NetworkConfig(encoder_kind=kind, encoder=enc,
                        head=MLPConfig(num_inputs=32, num_outputs=2,
                                       hidden_size=(64,)), latent_dim=32)
    evo = EvoDQN(env, net, optax.adam(1e-3), num_envs=16, steps_per_iter=128,
                 buffer_size=10_000, batch_size=64)

    pop = evo.init_population(jax.random.PRNGKey(42), pop_size=pop_size)
    generation = evo.make_pod_generation(mesh)  # shard_map over the pop axis

    for gen_idx in range(8):
        pop, fitness = generation(pop, jax.random.PRNGKey(gen_idx))
        print(f"generation {gen_idx}: fitness "
              f"mean {float(np.mean(fitness)):6.1f} "
              f"max {float(np.max(fitness)):6.1f}")
    print("done — best member fitness:", float(np.max(fitness)))
