"""Recurrent PPO on velocity-masked CartPole
(parity: demos/demo_on_policy_rnn_cartpole.py — the reference masks velocities
so the task becomes a POMDP: a flat MLP policy plateaus, an LSTM policy that
integrates positions over time solves it).

Toggle RECURRENT to compare; both run the same trainer and rollout collector
(agilerl_tpu/rollouts/on_policy.py branches on agent.recurrent)."""

# allow running directly as `python <dir>/<script>.py` from a source checkout
import os as _os, sys as _sys  # noqa: E402
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
if _os.environ.get("JAX_PLATFORMS"):  # some plugin backends ignore the env var
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import gymnasium as gym
import jax.numpy as jnp
import numpy as np

from agilerl_tpu.algorithms import PPO
from agilerl_tpu.envs import CartPole, JaxVecEnv
from agilerl_tpu.rollouts.on_policy import collect_rollouts

RECURRENT = True  # False -> flat MLP PPO on the same POMDP (plateaus)


class MaskedVelocityCartPole(CartPole):
    """CartPole observing only (x, theta) — velocities hidden (POMDP)."""

    observation_space = gym.spaces.Box(
        low=np.array([-4.8, -0.418], np.float32),
        high=np.array([4.8, 0.418], np.float32),
    )

    def reset_fn(self, key):
        state, obs = super().reset_fn(key)
        return state, obs[jnp.array([0, 2])]

    def step_fn(self, state, action, key):
        state, obs, reward, terminated, truncated = super().step_fn(
            state, action, key
        )
        return state, obs[jnp.array([0, 2])], reward, terminated, truncated


if __name__ == "__main__":
    num_envs = 16
    env = JaxVecEnv(MaskedVelocityCartPole(), num_envs=num_envs, seed=0)
    net_config = {"latent_dim": 64, "recurrent": RECURRENT}
    if RECURRENT:
        net_config["encoder_config"] = {"hidden_size": 64}
    else:
        net_config["encoder_config"] = {"hidden_size": (64,)}
    agent = PPO(
        env.single_observation_space, env.single_action_space,
        num_envs=num_envs, learn_step=256, batch_size=256, update_epochs=4,
        lr=2e-3, gamma=0.99, gae_lambda=0.95, ent_coef=0.01,
        recurrent=RECURRENT, net_config=net_config, seed=0,
    )
    print(f"===== Recurrent PPO on velocity-masked CartPole "
          f"(recurrent={RECURRENT}) =====")
    for it in range(40):
        collect_rollouts(agent, env, n_steps=agent.learn_step)
        agent.learn()
        if it % 5 == 0:
            fitness = agent.test(env, max_steps=500, loop=1)
            print(f"iter {it:3d}  fitness {fitness:7.1f}  (solved ~500)")
    print("final fitness:", agent.test(env, max_steps=500, loop=3))
