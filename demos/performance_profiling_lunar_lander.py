"""Profiling demo — host-env boundary workload (parity:
demos/performance_flamegraph_lunar_lander.py).

Unlike performance_profiling_cartpole.py (pure on-device EvoPPO), this
profiles the OTHER regime: a gymnasium host env (LunarLander-v3) stepping in
subprocesses while DQN's jitted get_action/learn run on device — the regime
where the host<->device boundary dominates. The jax.profiler trace shows the
device gaps; StepTimer breaks out action/env/learn wall time."""

# allow running directly as `python <dir>/<script>.py` from a source checkout
import os as _os, sys as _sys  # noqa: E402
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
if _os.environ.get("JAX_PLATFORMS"):  # some plugin backends ignore the env var
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import time

import numpy as np

from agilerl_tpu.components import ReplayBuffer
from agilerl_tpu.utils.profiling import profile_trace
from agilerl_tpu.utils.utils import create_population, make_vect_envs

if __name__ == "__main__":
    num_envs = 8
    env = make_vect_envs("LunarLander-v3", num_envs=num_envs)
    agent = create_population(
        "DQN", env.single_observation_space, env.single_action_space,
        population_size=1,
        net_config={"latent_dim": 64, "encoder_config": {"hidden_size": (128,)}},
        INIT_HP={"BATCH_SIZE": 128, "LR": 1e-3, "DOUBLE": True},
        seed=0,
    )[0]
    memory = ReplayBuffer(max_size=20_000)

    obs, _ = env.reset(seed=0)
    t_act = t_env = t_learn = 0.0
    steps = 512
    with profile_trace("/tmp/agilerl_tpu_trace_lander"):
        for i in range(steps):
            t0 = time.perf_counter()
            action = agent.get_action(obs, epsilon=0.5)
            t1 = time.perf_counter()
            next_obs, reward, term, trunc, _ = env.step(action)
            t2 = time.perf_counter()
            memory.add({
                "obs": obs, "action": action,
                "reward": np.asarray(reward, np.float32),
                "next_obs": next_obs,
                "done": np.asarray(term | trunc, np.float32),
            }, batched=True)
            if len(memory) >= 256 and i % 4 == 0:
                agent.learn(memory.sample(agent.batch_size))
            t3 = time.perf_counter()
            obs = next_obs
            t_act += t1 - t0
            t_env += t2 - t1
            t_learn += t3 - t2
    env.close()
    total = t_act + t_env + t_learn
    print("trace written to /tmp/agilerl_tpu_trace_lander (open in TensorBoard)")
    print(f"wall-time split over {steps} iterations "
          f"({steps * num_envs} env-steps):")
    print(f"  get_action {t_act:6.2f}s ({100 * t_act / total:4.1f}%)")
    print(f"  env.step   {t_env:6.2f}s ({100 * t_env / total:4.1f}%)  "
          f"<- the host boundary the JAX-native envs remove")
    print(f"  learn      {t_learn:6.2f}s ({100 * t_learn / total:4.1f}%)")
