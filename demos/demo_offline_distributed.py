"""Distributed offline RL (CQN) — data-parallel learning over a device mesh
(parity: demos/demo_offline_distributed.py, where the reference shards replay
batches across Accelerate DDP ranks).

The TPU-native shape: params stay replicated, each sampled batch is placed
with a `NamedSharding` that splits the batch axis over the `dp` mesh axis, and
GSPMD compiles the SAME jitted train step into a data-parallel program — the
gradient all-reduce the reference gets from DDP hooks is inserted by XLA as an
ICI psum. No launcher, no process groups, identical numerics to 1 device.

Run on a host with one device via a virtual 8-device CPU mesh:
    JAX_PLATFORMS=cpu python demos/demo_offline_distributed.py
"""

# allow running directly as `python <dir>/<script>.py` from a source checkout
import os as _os, sys as _sys  # noqa: E402
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
if _os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    _flags = _os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        _os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from agilerl_tpu.components import ReplayBuffer
from agilerl_tpu.utils.minari_utils import collect_offline_dataset
from agilerl_tpu.utils.utils import create_population, make_vect_envs


def shard_batch(batch, sharding):
    """Split the batch axis of every leaf across the dp mesh axis."""
    return jax.tree.map(
        lambda x: jax.device_put(jnp.asarray(x), sharding), dict(batch)
    )


if __name__ == "__main__":
    devices = jax.devices()
    mesh = Mesh(np.asarray(devices), axis_names=("dp",))
    data_sharding = NamedSharding(mesh, P("dp"))
    print(f"===== agilerl_tpu distributed offline demo =====\n"
          f"devices: {len(devices)} ({devices[0].platform}) — dp axis")

    env = make_vect_envs("CartPole-v1", num_envs=8)
    dataset = collect_offline_dataset(env, steps=10_000, epsilon=1.0)
    memory = ReplayBuffer(max_size=len(dataset["rewards"]))
    memory.add({
        "obs": np.asarray(dataset["observations"]),
        "action": np.asarray(dataset["actions"]).squeeze(),
        "reward": np.asarray(dataset["rewards"], np.float32).squeeze(),
        "next_obs": np.asarray(dataset["next_observations"]),
        "done": np.asarray(dataset["terminals"], np.float32).squeeze(),
    }, batched=True)

    # batch size must divide evenly across the dp axis
    batch_size = 128 * len(devices) if len(devices) > 1 else 128
    agent = create_population(
        "CQN", env.single_observation_space, env.single_action_space,
        population_size=1,
        net_config={"latent_dim": 32, "encoder_config": {"hidden_size": (64,)}},
        INIT_HP={"BATCH_SIZE": batch_size, "LR": 1e-3},
        seed=42,
    )[0]

    for step in range(200):
        batch = memory.sample(batch_size)
        loss = agent.learn(shard_batch(batch, data_sharding))
        if step % 50 == 0:
            print(f"step {step:4d}  cql loss {float(loss):8.4f}")

    fitness = agent.test(env, max_steps=500, loop=3)
    env.close()
    print(f"done — offline-trained fitness over 3 eval episodes: {fitness:.1f}")
