"""Evolutionary DQN on CartPole (parity: demos/demo_off_policy.py in the
reference — create_population -> train_off_policy with tournament+mutations)."""

# allow running directly as `python <dir>/<script>.py` from a source checkout
import os as _os, sys as _sys  # noqa: E402
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
if _os.environ.get("JAX_PLATFORMS"):  # some plugin backends ignore the env var
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import numpy as np

from agilerl_tpu.components import ReplayBuffer
from agilerl_tpu.hpo import Mutations, TournamentSelection
from agilerl_tpu.training.train_off_policy import train_off_policy
from agilerl_tpu.utils.utils import create_population, make_vect_envs

if __name__ == "__main__":
    NET_CONFIG = {"latent_dim": 32, "encoder_config": {"hidden_size": (64,)}}
    INIT_HP = {"BATCH_SIZE": 64, "LR": 1e-3, "GAMMA": 0.99, "LEARN_STEP": 4,
               "TAU": 1e-2, "DOUBLE": True, "POP_SIZE": 4}

    env = make_vect_envs("CartPole-v1", num_envs=16)
    pop = create_population(
        "DQN", env.single_observation_space, env.single_action_space,
        net_config=NET_CONFIG, INIT_HP=INIT_HP, seed=42,
    )
    memory = ReplayBuffer(max_size=20_000, seed=42)
    tournament = TournamentSelection(tournament_size=2, elitism=True,
                                     population_size=4, eval_loop=1)
    mutations = Mutations(no_mutation=0.4, architecture=0.2, new_layer_prob=0.2,
                          parameters=0.2, activation=0.0, rl_hp=0.2)

    pop, fitnesses = train_off_policy(
        env, "CartPole-v1", "DQN", pop, memory,
        max_steps=50_000, evo_steps=5_000, eval_steps=None, eval_loop=1,
        eps_start=1.0, eps_end=0.1, eps_decay=0.999,
        tournament=tournament, mutation=mutations, verbose=True,
    )
    print("best fitness:", max(max(f) for f in fitnesses))
