"""GRPO LLM finetuning demo (parity: the reference's
benchmarking/benchmarking_grpo.py workload — Qwen2.5-0.5B-Instruct on
Countdown-style tasks — runs through llm/hf.load_hf_model when weights are
available locally; this demo uses the in-tree char-level model so it runs
anywhere, swap `load_hf_model("Qwen/Qwen2.5-0.5B-Instruct")` in for the real
workload)."""

# allow running directly as `python <dir>/<script>.py` from a source checkout
import os as _os, sys as _sys  # noqa: E402
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
if _os.environ.get("JAX_PLATFORMS"):  # some plugin backends ignore the env var
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np

from agilerl_tpu.algorithms.grpo import GRPO
from agilerl_tpu.hpo import Mutations, TournamentSelection
from agilerl_tpu.llm import model as M
from agilerl_tpu.training.train_llm import finetune_llm_reasoning
from agilerl_tpu.utils.llm_utils import CharTokenizer, ReasoningGym


def make_dataset(n, seed):
    rng = np.random.default_rng(seed)
    rows = []
    for _ in range(n):
        a, b = rng.integers(0, 10, 2)
        rows.append({"question": f"{a}+{b}=", "answer": str(a + b)})
    return rows


def reward_fn(completion, answer, prompt):
    return 1.0 if completion.strip().startswith(str(answer)) else 0.0


if __name__ == "__main__":
    tok = CharTokenizer()
    cfg = M.GPTConfig(vocab_size=tok.vocab_size, n_layer=4, n_head=4,
                      d_model=128, max_seq_len=64)
    env = ReasoningGym(make_dataset(512, 0), make_dataset(64, 1), tok,
                       reward_fn=reward_fn, data_batch_size=8)
    pop = [
        GRPO(config=cfg, pad_token_id=tok.pad_token_id, eos_token_id=tok.eos_token_id,
             group_size=8, batch_size=16, max_output_tokens=4, lr=1e-4, index=i, seed=i)
        for i in range(2)
    ]
    # share one frozen base across the population (adapters differ)
    for agent in pop[1:]:
        agent.base_params = pop[0].base_params
    tournament = TournamentSelection(2, True, 2, eval_loop=1)
    mutations = Mutations(no_mutation=0.5, architecture=0.0, parameters=0.0,
                          activation=0.0, rl_hp=0.5)
    pop, fitnesses = finetune_llm_reasoning(
        pop, env, max_steps=100, evaluation_interval=10,
        tournament=tournament, mutation=mutations, verbose=True,
    )
