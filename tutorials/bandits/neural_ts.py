"""Tutorial — NeuralTS contextual bandit on a labelled dataset
(parity: tutorials/bandits/neural_ts.py — PenDigits is replaced by a
synthetic separable classification task; swap in any (features, labels))."""

# allow running directly as `python tutorials/<dir>/<script>.py` from a source checkout
import os as _os, sys as _sys  # noqa: E402
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))))
if _os.environ.get("JAX_PLATFORMS"):  # some plugin backends ignore the env var
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import numpy as np

from agilerl_tpu.components import ReplayBuffer
from agilerl_tpu.hpo import Mutations, TournamentSelection
from agilerl_tpu.training.train_bandits import train_bandits
from agilerl_tpu.utils.utils import create_population
from agilerl_tpu.wrappers import BanditEnv

if __name__ == "__main__":
    rng = np.random.default_rng(0)
    n, d, k = 600, 8, 4
    centers = rng.normal(size=(k, d)) * 2.0
    labels = rng.integers(0, k, n)
    features = centers[labels] + rng.normal(size=(n, d)) * 0.5
    env = BanditEnv(features, labels)

    pop = create_population(
        "NeuralTS", env.observation_space, env.action_space,
        population_size=4, seed=42,
        net_config={"latent_dim": 32, "encoder_config": {"hidden_size": (64,)}},
        INIT_HP={"BATCH_SIZE": 64, "LR": 1e-3, "LAMBDA": 1.0, "REG": 0.000625,
                 "LEARN_STEP": 2},
    )
    pop, fitnesses = train_bandits(
        env, "synthetic-bandit", "NeuralTS", pop, ReplayBuffer(max_size=10_000),
        max_steps=6_000, episode_steps=100, evo_steps=1_000,
        tournament=TournamentSelection(2, True, 4, 1),
        mutation=Mutations(no_mutation=0.4, architecture=0.2, parameters=0.2,
                           activation=0.0, rl_hp=0.2),
    )
    print("best regret-free fitness:", max(max(f) for f in fitnesses))
