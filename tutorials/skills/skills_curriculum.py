"""Tutorial — curriculum learning with Skill wrappers
(parity: tutorials/skills/agilerl_skills_curriculum.py — shaped-reward skills
train in sequence before the full task)."""

# allow running directly as `python tutorials/<dir>/<script>.py` from a source checkout
import os as _os, sys as _sys  # noqa: E402
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))))
if _os.environ.get("JAX_PLATFORMS"):  # some plugin backends ignore the env var
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import numpy as np

from agilerl_tpu.components import ReplayBuffer
from agilerl_tpu.envs import CartPole, JaxVecEnv
from agilerl_tpu.training.train_off_policy import train_off_policy
from agilerl_tpu.utils.utils import create_population
from agilerl_tpu.wrappers import Skill


class StabilizeSkill(Skill):
    """Reward keeping the pole near vertical (ignore cart position)."""

    def skill_reward(self, obs, reward, terminated, truncated, info):
        angle = np.asarray(obs)[..., 2]
        return obs, 1.0 - np.abs(angle) * 10.0, terminated, truncated, info


class CenterSkill(Skill):
    """Reward keeping the cart near the centre of the track."""

    def skill_reward(self, obs, reward, terminated, truncated, info):
        x = np.asarray(obs)[..., 0]
        return obs, 1.0 - np.abs(x), terminated, truncated, info


if __name__ == "__main__":
    base = JaxVecEnv(CartPole(), num_envs=8, seed=0)
    pop = create_population(
        "DQN", base.single_observation_space, base.single_action_space,
        population_size=1, seed=42,
        net_config={"latent_dim": 32, "encoder_config": {"hidden_size": (64,)}},
        INIT_HP={"BATCH_SIZE": 64, "LR": 1e-3, "LEARN_STEP": 8},
    )
    memory = ReplayBuffer(max_size=50_000)
    # curriculum: each skill shapes the reward for a phase, then the full task
    for phase, env in (("stabilize", StabilizeSkill(base)),
                       ("center", CenterSkill(base)),
                       ("full", base)):
        pop, fitnesses = train_off_policy(
            env, f"cartpole-{phase}", "DQN", pop, memory,
            max_steps=pop[0].steps[-1] + 8_000, evo_steps=2_000, verbose=False,
        )
        print(f"{phase}: fitness {fitnesses[0][-1]:.1f}")
