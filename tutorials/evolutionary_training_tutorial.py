"""Tutorial 2 — The full evolutionary loop on CartPole (pure-JAX env).

Run: python tutorials/evolutionary_training_tutorial.py
"""

# allow running directly as `python <dir>/<script>.py` from a source checkout
import os as _os, sys as _sys  # noqa: E402
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
if _os.environ.get("JAX_PLATFORMS"):  # some plugin backends ignore the env var
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

from agilerl_tpu.components import ReplayBuffer
from agilerl_tpu.hpo import Mutations, TournamentSelection
from agilerl_tpu.training.train_off_policy import train_off_policy
from agilerl_tpu.utils.utils import create_population, make_vect_envs

env = make_vect_envs("CartPole-v1", num_envs=8)   # JAX env, autoreset, vmapped
pop = create_population(
    "DQN", env.single_observation_space, env.single_action_space,
    population_size=4, INIT_HP={"BATCH_SIZE": 64, "LR": 1e-3, "LEARN_STEP": 4},
    net_config={"latent_dim": 32, "encoder_config": {"hidden_size": (64,)}},
)
pop, fitnesses = train_off_policy(
    env, "CartPole-v1", "DQN", pop, ReplayBuffer(max_size=20_000),
    max_steps=20_000, evo_steps=4_000,
    tournament=TournamentSelection(2, True, 4, 1),
    mutation=Mutations(no_mutation=0.4, architecture=0.2, parameters=0.2,
                       activation=0.0, rl_hp=0.2),
)
print("best fitness:", max(max(f) for f in fitnesses))
