"""Tutorial 4 — The full parallelism menu on one virtual pod: fsdp/tp for a
dense GPT, ep for a Mixture-of-Experts, pp for a GPipe pipeline, all on an
8-device CPU mesh (the same code runs unchanged on a TPU pod slice).

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
         python tutorials/parallelism_menu_tutorial.py
"""

# allow running directly as `python <dir>/<script>.py` from a source checkout
import os as _os, sys as _sys  # noqa: E402
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
_os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = _os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    _os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from agilerl_tpu.llm import model as M
from agilerl_tpu.parallel.mesh import make_mesh
from agilerl_tpu.parallel.pipeline import pipeline_apply
from agilerl_tpu.parallel.plan import grpo_plan_for_mesh, make_grpo_plan

devices = jax.devices()[:8]
print(f"devices: {len(devices)} x {devices[0].platform}")

tokens = jnp.asarray(np.random.default_rng(0).integers(1, 250, size=(8, 32)), jnp.int32)
targets = jnp.roll(tokens, -1, axis=1)


def ce_loss(cfg, params, aux_weight=0.0):
    if aux_weight:
        logits, _, aux = M.apply(cfg, params, tokens, return_aux=True)
    else:
        (logits, _), aux = M.apply(cfg, params, tokens), 0.0
    lp = jax.nn.log_softmax(logits, -1)
    return -jnp.take_along_axis(lp, targets[..., None], -1).mean() + aux_weight * aux


# -- 1. Dense GPT on an fsdp x tp mesh (ZeRO + megatron-style TP) ----------- #
mesh = make_mesh(dp=1, fsdp=4, tp=2, devices=devices)
cfg = M.GPTConfig(vocab_size=256, n_layer=2, n_head=4, d_model=64,
                  max_seq_len=32, dtype=jnp.float32)
params = M.init_params(jax.random.PRNGKey(0), cfg)
# declarative: the built-in GRPO rule set resolved for this mesh (regex
# rules -> PartitionSpecs; axes the mesh lacks degrade to replication)
params = grpo_plan_for_mesh(mesh).place("params", params, mesh)
with mesh:
    loss, grads = jax.jit(jax.value_and_grad(lambda p: ce_loss(cfg, p)))(params)
print(f"1. fsdp=4 x tp=2 dense GPT: loss {float(loss):.4f} (grads sharded like params)")

# -- 2. MoE GPT with experts sharded on ep ---------------------------------- #
ep_mesh = make_mesh(dp=1, fsdp=1, tp=1, ep=8, devices=devices)
moe_cfg = M.GPTConfig(vocab_size=256, n_layer=2, n_head=4, d_model=64,
                      max_seq_len=32, dtype=jnp.float32,
                      n_experts=8, expert_top_k=2)
moe_params = M.init_params(jax.random.PRNGKey(1), moe_cfg)
moe_params = make_grpo_plan(ep=8).place("params", moe_params, ep_mesh)
with ep_mesh:
    moe_loss = jax.jit(lambda p: ce_loss(moe_cfg, p, aux_weight=moe_cfg.router_aux_weight))(moe_params)
print(f"2. ep=8 MoE GPT (8 experts, top-2): loss+aux {float(moe_loss):.4f} "
      "(GSPMD inserts the all-to-all pair per layer)")

# -- 3. GPipe pipeline over pp ---------------------------------------------- #
pp_mesh = Mesh(np.asarray(devices), axis_names=("pp",))
pp_cfg = M.GPTConfig(vocab_size=256, n_layer=8, n_head=4, d_model=64,
                     max_seq_len=32, dtype=jnp.float32)
pp_params = M.init_params(jax.random.PRNGKey(2), pp_cfg)
logits = pipeline_apply(pp_cfg, pp_params, tokens, pp_mesh, num_microbatches=4)
print(f"3. pp=8 GPipe (8 stages x 1 layer, 4 microbatches): logits {logits.shape}, "
      f"finite={bool(jnp.isfinite(logits).all())}")

print("done — the same specs scale to real ICI meshes by swapping the device list")
