"""Tutorial — MATD3 on a cooperative multi-agent env
(parity: tutorials/pettingzoo/matd3.py — space_invaders/simple_speaker
become the pure-JAX SimpleSpread so rollouts run under jit; any PettingZoo
parallel env works via vector.PettingZooVecEnv)."""

# allow running directly as `python tutorials/<dir>/<script>.py` from a source checkout
import os as _os, sys as _sys  # noqa: E402
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))))
if _os.environ.get("JAX_PLATFORMS"):  # some plugin backends ignore the env var
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import numpy as np

from agilerl_tpu.components import MultiAgentReplayBuffer
from agilerl_tpu.envs.multi_agent import MultiAgentJaxVecEnv, SimpleSpreadJax
from agilerl_tpu.hpo import Mutations, TournamentSelection
from agilerl_tpu.training.train_multi_agent_off_policy import (
    train_multi_agent_off_policy,
)
from agilerl_tpu.utils.utils import create_population

if __name__ == "__main__":
    env = MultiAgentJaxVecEnv(SimpleSpreadJax(n_agents=3), num_envs=8, seed=0)
    pop = create_population(
        "MATD3", env.observation_spaces, env.action_spaces,
        agent_ids=env.agent_ids, population_size=4, seed=42,
        net_config={"latent_dim": 32, "encoder_config": {"hidden_size": (64,)}},
        INIT_HP={"BATCH_SIZE": 64, "LEARN_STEP": 8},
    )
    memory = MultiAgentReplayBuffer(max_size=100_000, agent_ids=env.agent_ids)
    pop, fitnesses = train_multi_agent_off_policy(
        env, "simple-spread", "MATD3", pop, memory,
        max_steps=20_000, evo_steps=2_000,
        tournament=TournamentSelection(2, True, 4, 1),
        mutation=Mutations(no_mutation=0.4, architecture=0.2, parameters=0.2,
                           activation=0.0, rl_hp=0.2),
    )
    print("best fitness:", max(max(f) for f in fitnesses))
