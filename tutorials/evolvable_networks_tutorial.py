"""Tutorial 1 — Evolvable networks: configs, mutations, weight preservation.

The core idea (vs the reference's torch-module mutation): a module is a frozen
architecture config + a params pytree. A mutation is a pure config transition;
weights transfer slab-wise. Run: python tutorials/evolvable_networks_tutorial.py
"""

# allow running directly as `python <dir>/<script>.py` from a source checkout
import os as _os, sys as _sys  # noqa: E402
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
if _os.environ.get("JAX_PLATFORMS"):  # some plugin backends ignore the env var
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import jax
import jax.numpy as jnp

from agilerl_tpu.modules import EvolvableMLP

mlp = EvolvableMLP(num_inputs=4, num_outputs=2, hidden_size=(64, 64),
                   key=jax.random.PRNGKey(0))
print("config:", mlp.config)
print("forward:", mlp(jnp.ones((1, 4))).shape)

# grow a layer: weights of existing layers are preserved exactly
w0 = mlp.params["layer_0"]["kernel"]
mlp.add_layer()
assert (mlp.params["layer_0"]["kernel"] == w0).all()
print("after add_layer:", mlp.config.hidden_size)

# node mutations keep the overlapping slab
info = mlp.add_node(hidden_layer=0, numb_new_nodes=32)
print("after add_node:", mlp.config.hidden_size, info)

# the HPO engine samples mutations like this:
import numpy as np
print("sampled mutation:", mlp.sample_mutation_method(rng=np.random.default_rng(0)))
