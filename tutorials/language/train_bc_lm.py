"""Tutorial — behavioural cloning on language (BC_LM baseline for ILQL)
(parity: tutorials/language/train_bc_lm.py)."""

# allow running directly as `python tutorials/<dir>/<script>.py` from a source checkout
import os as _os, sys as _sys  # noqa: E402
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))))
if _os.environ.get("JAX_PLATFORMS"):  # some plugin backends ignore the env var
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import numpy as np

from agilerl_tpu.algorithms.ilql import BC_LM
from agilerl_tpu.data.rl_data import Language_Observation, RL_Dataset
from agilerl_tpu.llm.model import GPTConfig
from agilerl_tpu.utils.llm_utils import CharTokenizer

if __name__ == "__main__":
    tok = CharTokenizer()
    cfg = GPTConfig(vocab_size=tok.vocab_size, n_layer=2, n_head=4, d_model=64,
                    max_seq_len=32)
    rng = np.random.default_rng(0)
    obs = [
        Language_Observation(sequence=[(f"{a}+1=", None), (str(a + 1), 1.0)])
        for a in rng.integers(0, 5, 256)
    ]
    ds = RL_Dataset(obs, tok, max_len=10)
    agent = BC_LM(config=cfg, lr=1e-3, seed=0)
    for step in range(200):
        loss = agent.learn(ds.sample_batch(16, rng))
        if step % 50 == 0:
            print(f"[{step}] bc loss {loss:.4f}")
    # llm.generate takes LEFT-padded prompts and returns completions only
    ids = tok.encode("3+1=")
    prompt = np.asarray([[0] * 4 + ids], np.int32)
    mask = (prompt != 0).astype(np.float32)
    comp, comp_mask = agent.generate(prompt, mask, max_new_tokens=2,
                                     temperature=0.0)
    real = np.asarray(comp[0])[np.asarray(comp_mask[0], bool)]
    print("completion for 3+1= :", tok.decode([int(t) for t in real]))
