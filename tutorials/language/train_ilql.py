"""Tutorial — offline RL on language with ILQL
(parity: tutorials/language/train_ilql.py — the wordle dataset becomes a
synthetic rewarded-dialogue set; Language_Observation carries the same
(utterance, reward) structure)."""

# allow running directly as `python tutorials/<dir>/<script>.py` from a source checkout
import os as _os, sys as _sys  # noqa: E402
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))))
if _os.environ.get("JAX_PLATFORMS"):  # some plugin backends ignore the env var
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import numpy as np

from agilerl_tpu.algorithms.ilql import ILQL, ILQL_Policy, TopAdvantageNGrams
from agilerl_tpu.data.rl_data import Language_Observation, RL_Dataset
from agilerl_tpu.llm.model import GPTConfig
from agilerl_tpu.utils.llm_utils import CharTokenizer

if __name__ == "__main__":
    tok = CharTokenizer()
    cfg = GPTConfig(vocab_size=tok.vocab_size, n_layer=2, n_head=4, d_model=64,
                    max_seq_len=32)
    rng = np.random.default_rng(0)
    obs = []
    for _ in range(256):
        a = int(rng.integers(0, 5))
        good = rng.random() < 0.5
        answer = str(a + 1) if good else str(a)
        obs.append(Language_Observation(
            sequence=[(f"{a}+1=", None), (answer, 1.0 if good else -1.0)],
        ))
    ds = RL_Dataset(obs, tok, max_len=10)

    agent = ILQL(config=cfg, lr=1e-3, seed=0)
    for step in range(200):
        loss = agent.learn(ds.sample_batch(16, rng))
        if step % 50 == 0:
            print(f"[{step}] ilql loss {loss:.4f}")

    # what did the Q function decide is good text?
    probe = TopAdvantageNGrams(tokenizer=tok, n_gram=2, print_k=5)
    probe.evaluate(agent, ds.sample_batch(64, rng))
    print("top-advantage n-grams:", probe.top())

    # act with the learned policy
    policy = ILQL_Policy(agent, kind="beam", max_new_tokens=2, beam_width=4)
    prompt = np.asarray([tok.encode("3+1=") + [0] * 4], np.int32)
    mask = (prompt != 0).astype(np.float32)
    out_tokens, out_mask = policy.act(prompt, mask)
    real = out_tokens[0][np.asarray(out_mask[0], bool)]
    print("generation:", tok.decode([int(t) for t in real]))
