"""Tutorial 3 — Pod-scale population parallelism: the whole evolutionary loop
as one SPMD program, one population member per device.

Run on any host (uses however many devices jax sees; on CPU set
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu).
"""

# allow running directly as `python <dir>/<script>.py` from a source checkout
import os as _os, sys as _sys  # noqa: E402
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
if _os.environ.get("JAX_PLATFORMS"):  # some plugin backends ignore the env var
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import jax
import numpy as np
import optax
from jax.sharding import Mesh

from agilerl_tpu.envs import CartPole
from agilerl_tpu.modules.mlp import MLPConfig
from agilerl_tpu.networks import distributions as D
from agilerl_tpu.networks.base import NetworkConfig, default_encoder_config
from agilerl_tpu.parallel.population import EvoPPO

env = CartPole()
kind, enc = default_encoder_config(env.observation_space, latent_dim=32,
                                   encoder_config={"hidden_size": (64,)})
evo = EvoPPO(
    env,
    NetworkConfig(encoder_kind=kind, encoder=enc,
                  head=MLPConfig(num_inputs=32, num_outputs=2), latent_dim=32),
    NetworkConfig(encoder_kind=kind, encoder=enc,
                  head=MLPConfig(num_inputs=32, num_outputs=1), latent_dim=32),
    D.dist_config_from_space(env.action_space),
    optax.adam(3e-4), num_envs=32, rollout_len=32,
)
n = len(jax.devices())
pop = evo.init_population(jax.random.PRNGKey(0), pop_size=n)
mesh = Mesh(np.asarray(jax.devices()), axis_names=("pop",))
gen = evo.make_pod_generation(mesh)   # shard_map: fitness all-gather over ICI
for i in range(5):
    pop, fitness = gen(pop, jax.random.PRNGKey(i))
    print(f"gen {i}: fitness {np.asarray(fitness).round(1)}")
