"""Tutorial — GRPO reasoning finetune on arithmetic tasks
(parity: tutorials/llm_finetuning/grpo_reasoning.py — Countdown-Tasks +
Qwen2.5 become a char-tokenised arithmetic gym + in-tree GPT so the tutorial
runs anywhere; swap CFG/tokenizer for llm/hf.py-imported real weights)."""

# allow running directly as `python tutorials/<dir>/<script>.py` from a source checkout
import os as _os, sys as _sys  # noqa: E402
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))))
if _os.environ.get("JAX_PLATFORMS"):  # some plugin backends ignore the env var
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np

from agilerl_tpu.algorithms.grpo import GRPO
from agilerl_tpu.llm import model as M
from agilerl_tpu.training.train_llm import finetune_llm_reasoning
from agilerl_tpu.utils.llm_utils import CharTokenizer, ReasoningGym


def make_rows(n, seed):
    rng = np.random.default_rng(seed)
    return [{"question": f"{a}+{b}=", "answer": str(a + b)}
            for a, b in rng.integers(0, 10, (n, 2))]


def reward_fn(completion, answer, prompt):
    return float(completion.strip().startswith(str(answer)))


if __name__ == "__main__":
    tok = CharTokenizer()
    cfg = M.GPTConfig(vocab_size=tok.vocab_size, n_layer=4, n_head=4,
                      d_model=128, max_seq_len=64, dtype=jnp.float32)
    env = ReasoningGym(make_rows(256, 0), make_rows(64, 1), tok,
                       reward_fn=reward_fn, data_batch_size=8)
    agent = GRPO(config=cfg, pad_token_id=tok.pad_token_id,
                 eos_token_id=tok.eos_token_id, group_size=4, batch_size=32,
                 max_output_tokens=6, lr=1e-4, seed=0)
    pop, fitnesses = finetune_llm_reasoning(
        [agent], env, max_steps=60, evaluation_interval=10,
    )
    print("final accuracy:", fitnesses[0][-1])
