"""Tutorial — GRPO reasoning finetune WITH evolutionary HPO over a population
(parity: tutorials/llm_finetuning/grpo_reasoning_hpo.py — only RL
hyperparameters mutate for LLMs; base weights are shared across members)."""

# allow running directly as `python tutorials/<dir>/<script>.py` from a source checkout
import os as _os, sys as _sys  # noqa: E402
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))))
if _os.environ.get("JAX_PLATFORMS"):  # some plugin backends ignore the env var
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np

from agilerl_tpu.algorithms.grpo import GRPO
from agilerl_tpu.hpo import Mutations, TournamentSelection
from agilerl_tpu.llm import model as M
from agilerl_tpu.training.train_llm import finetune_llm_reasoning
from agilerl_tpu.utils.llm_utils import CharTokenizer, ReasoningGym
from tutorials.llm_finetuning.grpo_reasoning import make_rows, reward_fn

if __name__ == "__main__":
    tok = CharTokenizer()
    cfg = M.GPTConfig(vocab_size=tok.vocab_size, n_layer=4, n_head=4,
                      d_model=128, max_seq_len=64, dtype=jnp.float32)
    env = ReasoningGym(make_rows(256, 0), make_rows(64, 1), tok,
                       reward_fn=reward_fn, data_batch_size=8)
    pop = [GRPO(config=cfg, pad_token_id=tok.pad_token_id,
                eos_token_id=tok.eos_token_id, group_size=4, batch_size=16,
                max_output_tokens=6, index=i, seed=i) for i in range(4)]
    for member in pop[1:]:
        member.base_params = pop[0].base_params  # share the frozen base
    pop, fitnesses = finetune_llm_reasoning(
        pop, env, max_steps=60, evaluation_interval=10,
        tournament=TournamentSelection(2, True, 4, 1),
        mutation=Mutations(no_mutation=0.5, architecture=0.0, parameters=0.0,
                           activation=0.0, rl_hp=0.5),
    )
    print("best accuracy:", max(f[-1] for f in fitnesses))
    print("surviving HPs:", [(a.lr, a.beta, a.group_size) for a in pop])
