#!/bin/bash
# Round-5 second-window manual capture: cheap kernel/XLA probes FIRST (the
# service compiles standalone kernels fine), the heavy unrolled-decode bench
# after, and the GRPO compile-poison bisection last (a wedged compile can
# poison the service for hours — see NOTES_ROUND5 item 10).
set -u
cd "$(dirname "$0")"
mkdir -p .tpu_results

probe() {
  timeout 150 python - <<'EOF' >/dev/null 2>&1
import jax, jax.numpy as jnp
assert jax.default_backend() != "cpu"
x = jnp.ones((256, 256), jnp.bfloat16)
jax.jit(lambda a: a @ a)(x).block_until_ready()
EOF
}

stage() {  # stage <artifact> <timeout_s> <cmd...>
  local artifact="$1" tmo="$2"; shift 2
  if [ -s ".tpu_results/$artifact" ]; then return 0; fi
  echo "[capture2 $(date -u +%H:%M:%S)] stage $artifact: $*"
  timeout "$tmo" "$@" > ".tpu_results/.$artifact.tmp" 2>&1
  local rc=$?
  if [ "$rc" -eq 0 ]; then
    # only a SUCCESSFUL run installs the artifact (a failure log would
    # satisfy the [-s] resume guard and block retries forever)
    mv ".tpu_results/.$artifact.tmp" ".tpu_results/$artifact" 2>/dev/null
  else
    mv ".tpu_results/.$artifact.tmp" ".tpu_results/$artifact.failed" 2>/dev/null
  fi
  echo "[capture2 $(date -u +%H:%M:%S)] stage $artifact rc=$rc"
  if ! probe; then
    echo "[capture2 $(date -u +%H:%M:%S)] service wedged after $artifact — waiting"
    until probe; do sleep 300; done
    echo "[capture2 $(date -u +%H:%M:%S)] service recovered"
  fi
}

until probe; do
  echo "[capture2 $(date -u +%H:%M:%S)] pool down"
  sleep 300
done
echo "[capture2 $(date -u +%H:%M:%S)] pool UP"

# -- cheap, proven-shape captures first --------------------------------------
stage followup_flash.log 1200 python benchmarking/tpu_followup.py flash
stage followup_fused_llama.log 1200 python benchmarking/tpu_followup.py fused_llama
stage followup_paged_kv.log 900 python benchmarking/tpu_followup.py paged_kv

# -- the decode bench (unrolled cached path; depth reduced for this service) --
stage bucketed_decode_l4.log 1500 env BENCH_DECODE_LAYERS=4 python benchmarking/bucketed_decode_bench.py

# -- GRPO compile-poison bisection (2-layer cells, fresh process each) --------
stage grpo_probe_noplas.log 600 env AGILERL_TPU_DISABLE_PALLAS=1 python benchmarking/grpo_compile_probe.py 2
stage grpo_probe_noscan.log 600 env AGILERL_TPU_DISABLE_SCAN_LAYERS=1 python benchmarking/grpo_compile_probe.py 2
stage grpo_probe_default.log 600 python benchmarking/grpo_compile_probe.py 2

echo "[capture2 $(date -u +%H:%M:%S)] queue COMPLETE — inspect grpo probes before the full bench"
